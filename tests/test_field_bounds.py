"""Interval-arithmetic proof that the raw-multiply carry discipline in
ops/ed25519/{field,point}.py never overflows int32.

field.mul_rr/sqr_rr perform NO input normalization; point.py inserts
F.carry1 exactly where needed.  This test mirrors the limb-level structure
of those functions with per-limb [lo, hi] int64 intervals and asserts that
every product, every partial column sum (in the same accumulation order as
the jnp code), and every carry intermediate stays inside int32.  If a
formula in point.py changes its carry discipline, the mirror here must be
updated to match -- the shapes of both are kept deliberately parallel.

It also proves closure: the coordinate intervals coming out of every point
op are contained in the "carried" interval assumed on input, so the dsm
loop is safe at any iteration count.
"""

import numpy as np
import pytest

from firedancer_tpu.ops.ed25519 import field as F

NL = F.NLIMB
RADIX = F.RADIX
MASK = F.MASK
FOLD = F.FOLD
I32_MIN, I32_MAX = -(2**31), 2**31 - 1


class IV:
    """Per-limb interval: lo/hi int64 arrays of shape (n,)."""

    def __init__(self, lo, hi):
        self.lo = np.asarray(lo, np.int64)
        self.hi = np.asarray(hi, np.int64)
        assert self.lo.shape == self.hi.shape
        assert np.all(self.lo <= self.hi)

    @property
    def n(self):
        return self.lo.shape[0]

    def assert32(self, what=""):
        assert np.all(self.lo >= I32_MIN) and np.all(self.hi <= I32_MAX), (
            what,
            int(self.lo.min()),
            int(self.hi.max()),
        )
        return self

    def __add__(self, o):
        return IV(self.lo + o.lo, self.hi + o.hi).assert32("add")

    def __sub__(self, o):
        return IV(self.lo - o.hi, self.hi - o.lo).assert32("sub")

    def __neg__(self):
        return IV(-self.hi, -self.lo)

    def __getitem__(self, sl):
        return IV(self.lo[sl], self.hi[sl])

    def scale(self, k: int):
        v = IV(
            np.minimum(self.lo * k, self.hi * k),
            np.maximum(self.lo * k, self.hi * k),
        )
        return v.assert32("scale")

    def hull(self, o):
        n = max(self.n, o.n)

        def pad(x, fill):
            return np.concatenate([x, np.full(n - len(x), fill, np.int64)])

        return IV(
            np.minimum(pad(self.lo, 0), pad(o.lo, 0)),
            np.maximum(pad(self.hi, 0), pad(o.hi, 0)),
        )

    def contains(self, o):
        return np.all(self.lo <= o.lo) and np.all(self.hi >= o.hi)

    @staticmethod
    def concat(*ivs):
        return IV(
            np.concatenate([v.lo for v in ivs]),
            np.concatenate([v.hi for v in ivs]),
        )

    @staticmethod
    def zeros(n):
        return IV(np.zeros(n, np.int64), np.zeros(n, np.int64))

    @staticmethod
    def uniform(n, lo, hi):
        return IV(np.full(n, lo, np.int64), np.full(n, hi, np.int64))


def _prod_iv(a: IV, b: IV) -> IV:
    """Interval of elementwise a*b (broadcasting row against rows)."""
    cands = [
        a.lo * b.lo,
        a.lo * b.hi,
        a.hi * b.lo,
        a.hi * b.hi,
    ]
    v = IV(np.minimum.reduce(cands), np.maximum.reduce(cands))
    return v.assert32("product")


# --- mirrors of field.py carry plumbing (same structure, interval domain)


def ipass(x: IV):
    # lo = x & MASK: sound over-approximation [0, MASK] unless interval
    # lies within one aligned 2^13 block
    same_block = (x.lo >> RADIX) == (x.hi >> RADIX)
    lo_lo = np.where(same_block, x.lo & MASK, 0)
    lo_hi = np.where(same_block, x.hi & MASK, MASK)
    lo = IV(lo_lo, lo_hi)
    hi = IV(x.lo >> RADIX, x.hi >> RADIX)
    shifted = IV.concat(IV.zeros(1), hi[:-1])
    return (lo + shifted).assert32("pass"), hi[-1:]


def iadd_at0(x: IV, v: IV):
    return IV.concat(x[0:1] + v, x[1:])


def icarry20(x: IV):
    # domain: any int32 (intermediates are checked by assert32 below)
    x, co = ipass(x)
    x = iadd_at0(x, co.scale(FOLD))
    x, co = ipass(x)
    return iadd_at0(x, co.scale(FOLD)).assert32("carry20")


def icarry1(x: IV):
    x, co = ipass(x)
    x = iadd_at0(x, co.scale(FOLD))
    l0 = x[0:1]
    same_block = (l0.lo >> RADIX) == (l0.hi >> RADIX)
    lo0 = IV(
        np.where(same_block, l0.lo & MASK, 0),
        np.where(same_block, l0.hi & MASK, MASK),
    )
    hi0 = IV(l0.lo >> RADIX, l0.hi >> RADIX)
    return IV.concat(lo0, x[1:2] + hi0, x[2:]).assert32("carry1")


def iplaced_sum(parts, total):
    out = None
    for off, arr in parts:
        v = IV.concat(
            *([IV.zeros(off)] if off else []),
            arr,
            *(
                [IV.zeros(total - off - arr.n)]
                if total - off - arr.n
                else []
            ),
        )
        out = v if out is None else (out + v).assert32("placed_sum")
    return out


def iconv_half(a: IV, b: IV):
    h = a.n
    parts = []
    for i in range(h):
        row = _prod_iv(IV(a.lo[i : i + 1], a.hi[i : i + 1]), b)
        parts.append((i, row))
    return iplaced_sum(parts, 2 * h - 1)


def isqr_half(a: IV):
    h = a.n
    a2 = a + a
    parts = []
    for i in range(h):
        ai = a[i : i + 1]
        row_src = IV.concat(ai, a2[i + 1 :]) if i + 1 < h else ai
        parts.append((2 * i, _prod_iv(ai, row_src)))
    return iplaced_sum(parts, 2 * h - 1)


H = NL // 2

# Karatsuba note on interval soundness: the computed mid = (z0 + z2) + m
# cancels algebraically to the cross-term columns (a0 b1 + a1 b0), but
# interval addition cannot see the cancellation (the dependency problem)
# and would raise a false alarm.  A signed int32 binary add is exact
# whenever its TRUE result fits int32, so it suffices to check (a) every
# product site, (b) the one genuine intermediate z0 + z2, and (c) the true
# values of mid and of the final columns via direct enclosures of the
# algebraically equal expressions.  The returned enclosure is the plain
# schoolbook conv interval, which bounds the true columns.


def iconv_full(a: IV, b: IV):
    n = a.n
    parts = [(i, _prod_iv(a[i : i + 1], b)) for i in range(n)]
    return iplaced_sum(parts, 2 * n + 1)


def iconv_k1(a: IV, b: IV):
    a0, a1 = a[:H], a[H:]
    b0, b1 = b[:H], b[H:]
    z0 = iconv_half(a0, b0)  # (a) product sites + column sums
    z2 = iconv_half(a1, b1)
    iconv_half(a0 - a1, b1 - b0)  # (a) the m-term product sites
    (z0 + z2).assert32("k1 z0+z2")  # (b)
    (iconv_half(a0, b1) + iconv_half(a1, b0)).assert32("k1 mid true")  # (c)
    return iconv_full(a, b)  # (c) final columns


def isqr_k1(a: IV):
    a0, a1 = a[:H], a[H:]
    z0 = isqr_half(a0)  # (a)
    z2 = isqr_half(a1)
    isqr_half(a0 - a1)  # (a)
    (z0 + z2).assert32("k1s z0+z2")  # (b)
    iconv_half(a0, a1).scale(2).assert32("k1s mid true")  # (c)
    return iconv_full(a, a)  # (c) final columns


def ireduce_conv(c: IV):
    c, _ = ipass(c)
    c, _ = ipass(c)
    lo, hi = c[:NL], c[NL:]
    lo = lo + hi[:NL].scale(FOLD)
    lo = iadd_at0(lo, hi[NL : NL + 1].scale(FOLD * FOLD))
    return icarry20(lo)


def imul_rr(a: IV, b: IV):
    return ireduce_conv(iconv_k1(a, b))


def isqr_rr(a: IV):
    return ireduce_conv(isqr_k1(a))


# --- point formula mirrors --------------------------------------------------

CANON = IV.uniform(NL, 0, MASK)


def idouble(p):
    x, y, z, _ = p
    a = isqr_rr(x)
    b = isqr_rr(y)
    c2 = isqr_rr(z)
    e = icarry1(isqr_rr(icarry1(x + y)) - a - b)
    g = b - a
    f = icarry1(g - c2 - c2)
    h = icarry1(-(a + b))
    return (imul_rr(e, f), imul_rr(g, h), imul_rr(f, g), imul_rr(e, h))


def iadd(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = imul_rr(y1 - x1, icarry1(y2 - x2))
    b = imul_rr(icarry1(y1 + x1), icarry1(y2 + x2))
    c = imul_rr(imul_rr(t1, CANON), t2)
    zz = imul_rr(z1, z2)
    e = icarry1(b - a)
    f = icarry1(zz + zz - c)
    g = icarry1(zz + zz + c)
    h = icarry1(b + a)
    return (imul_rr(e, f), imul_rr(g, h), imul_rr(f, g), imul_rr(e, h))


def iadd_niels(p, e):
    x1, y1, z1, t1 = p
    ypx, ymx, t2d, z2e = e
    a = imul_rr(y1 - x1, ymx)
    b = imul_rr(icarry1(y1 + x1), ypx)
    c = imul_rr(t1, t2d)
    d2 = imul_rr(z1, z2e)
    ec = icarry1(b - a)
    f = d2 - c
    g = icarry1(d2 + c)
    h = icarry1(b + a)
    return (imul_rr(ec, f), imul_rr(g, h), imul_rr(f, g), imul_rr(ec, h))


def iadd_niels_affine(p, e):
    x1, y1, z1, t1 = p
    ypx, ymx, t2d = e
    a = imul_rr(y1 - x1, ymx)
    b = imul_rr(icarry1(y1 + x1), ypx)
    c = imul_rr(t1, t2d)
    ec = icarry1(b - a)
    f = icarry1(z1 + z1 - c)
    g = icarry1(z1 + z1 + c)
    h = icarry1(b + a)
    return (imul_rr(ec, f), imul_rr(g, h), imul_rr(f, g), imul_rr(ec, h))


def _niels_entries(c: IV):
    """Interval of each niels coordinate after to_niels + lookup9.

    ypx/ymx are carry20 outputs (negation is a SWAP, no sign flip) hulled
    with the identity entry [0, 2]; t2d is a mul output hulled with its
    negation (lookup9 flips its sign); z2e is a carry20 output hulled with
    the identity's 2."""
    small = IV.uniform(NL, 0, 2)
    ypx = icarry20(c + c).hull(small)
    t2d_pos = imul_rr(c, CANON)
    t2d = t2d_pos.hull(-t2d_pos).hull(small)
    z2e = icarry20(c + c).hull(small)
    return ypx, t2d, z2e


def point_fixpoint():
    """Smallest self-consistent coordinate interval: closed under every
    point op used by the dsm loop (with table entries derived from it),
    and containing canonical limbs (identity / decompressed inputs)."""
    c = CANON
    for _ in range(10):
        p = (c, c, c, c)
        outs = []
        outs += list(idouble(p))
        outs += list(iadd(p, p))
        swap, t2d, z2e = _niels_entries(c)
        outs += list(iadd_niels(p, (swap, swap, t2d, z2e)))
        outs += list(iadd_niels_affine(p, (swap, swap, t2d)))
        # decompressed points: x is carry1(+-carried), y canonical,
        # z one, t = x*y
        xn = icarry1(c.hull(-c))
        outs += [xn, imul_rr(xn, icarry1(CANON))]
        nxt = CANON
        for o in outs:
            nxt = nxt.hull(o)
        if c.contains(nxt):
            return c
        c = c.hull(nxt)
    raise AssertionError("point coordinate interval did not converge")


PCOORD = point_fixpoint()


def _point():
    return (PCOORD, PCOORD, PCOORD, PCOORD)


def test_fixpoint_holds():
    # converged: one more application of every op stays inside PCOORD
    p = _point()
    swap, t2d, z2e = _niels_entries(PCOORD)
    for coord in (
        list(idouble(p))
        + list(iadd(p, p))
        + list(iadd_niels(p, (swap, swap, t2d, z2e)))
        + list(iadd_niels_affine(p, (swap, swap, t2d)))
    ):
        assert PCOORD.contains(coord)
    assert PCOORD.contains(imul_rr(PCOORD, PCOORD))
    assert PCOORD.contains(isqr_rr(PCOORD))


def test_decompress_chain():
    y = CANON  # from_bytes output
    ysq = isqr_rr(y)
    u = ysq - CANON
    v = icarry1(imul_rr(CANON, ysq) + CANON)
    v3 = imul_rr(isqr_rr(v), v)
    v7 = imul_rr(isqr_rr(v3), v)
    uc = icarry1(u)
    t = imul_rr(uc, v7)  # pow_p58 input; chain itself is mul/sqr of carried
    x = imul_rr(imul_rr(uc, v3), imul_rr(t, t))
    imul_rr(v, isqr_rr(x))
    # post-where x: hull with negation, then carry1; T = x * carry1(y)
    xn = icarry1(PCOORD.hull(-PCOORD))
    assert PCOORD.contains(imul_rr(xn, icarry1(CANON)))


def test_eq_external_inputs():
    # canonical() accepts |limb| <= 2^17: all eq inputs are raw subs or
    # carried values
    for v in (PCOORD, PCOORD - PCOORD, PCOORD.hull(-PCOORD)):
        assert np.all(np.abs(v.lo) <= 1 << 17)
        assert np.all(np.abs(v.hi) <= 1 << 17)
    zc = icarry1(PCOORD)
    imul_rr(icarry1(PCOORD), zc)


def test_mul_generic_contract():
    # F.mul accepts any |limb| <= 2^17 via carry20 on both sides
    loose = IV.uniform(NL, -(1 << 17), 1 << 17)
    imul_rr(icarry20(loose), icarry20(loose))
