"""Tests for the native tango layer: rings, flow control, dedup cache.

Modeled on the reference's test strategy (SURVEY.md §4.2): concurrency
tests spawn real producer/consumer threads against shared rings within one
process (reference: src/tango/test_frag_tx.c / test_frag_rx.c,
src/disco/dedup/test_dedup.c:654-660)."""

from __future__ import annotations

import threading

import numpy as np
import pytest

from firedancer_tpu.tango import (
    CNC,
    CNC_RUN,
    DCache,
    FSeq,
    MCache,
    TCache,
    Workspace,
    cr_avail,
)


@pytest.fixture
def wksp():
    return Workspace(8 << 20)


# ---------------------------------------------------------------------------
# mcache


def test_mcache_publish_poll(wksp):
    mc = MCache.create(wksp, "mc", depth=8)
    rc, frag, _ = mc.poll(0)
    assert rc == -1  # nothing published yet

    mc.publish(seq=0, sig=0xDEADBEEF, chunk=3, sz=100, ctl=3, tsorig=7, tspub=9)
    rc, frag, _ = mc.poll(0)
    assert rc == 0
    assert frag["sig"] == 0xDEADBEEF
    assert frag["chunk"] == 3
    assert frag["sz"] == 100
    assert frag["ctl"] == 3
    assert (frag["tsorig"], frag["tspub"]) == (7, 9)
    assert mc.seq_query() == 1


def test_mcache_overrun_detection(wksp):
    depth = 8
    mc = MCache.create(wksp, "mc", depth=depth)
    # producer laps the ring twice
    for seq in range(2 * depth + 3):
        mc.publish(seq=seq, sig=seq)
    # consumer still expecting seq 0 -> overrun
    rc, _, seq_now = mc.poll(0)
    assert rc == 1
    assert seq_now == 2 * depth  # line 0 now holds seq 16
    # recent seqs still readable
    rc, frag, _ = mc.poll(2 * depth + 2)
    assert rc == 0 and frag["sig"] == 2 * depth + 2


def test_mcache_drain_batch_and_overrun(wksp):
    depth = 16
    mc = MCache.create(wksp, "mc", depth=depth)
    for seq in range(10):
        mc.publish(seq=seq, sig=100 + seq)
    frags, seq, ovr = mc.drain(0, 64)
    assert len(frags) == 10 and seq == 10 and ovr == 0
    assert list(frags["sig"]) == [100 + i for i in range(10)]

    # now lap the consumer: publish 3*depth more
    for s in range(10, 10 + 3 * depth):
        mc.publish(seq=s, sig=100 + s)
    frags, seq2, ovr = mc.drain(seq, 1024)
    assert ovr > 0  # lost some
    assert seq2 == 10 + 3 * depth  # fully caught up
    # everything drained is a contiguous recent suffix
    assert list(frags["sig"]) == [100 + s for s in frags["seq"]]
    assert frags["seq"][-1] == 10 + 3 * depth - 1


def test_mcache_bad_depth(wksp):
    with pytest.raises(ValueError):
        MCache.footprint(12)


# ---------------------------------------------------------------------------
# dcache


def test_dcache_roundtrip_and_wrap(wksp):
    mtu, depth = 256, 4
    dc = DCache.create(wksp, "dc", mtu=mtu, depth=depth)
    payload = np.arange(100, dtype=np.uint8)
    seen_chunks = []
    for _ in range(50):  # enough to wrap several times
        c = dc.write(payload)
        seen_chunks.append(c)
        assert np.array_equal(dc.read(c, 100), payload)
    assert 0 in seen_chunks[1:]  # wrapped back to chunk 0


def test_dcache_read_batch(wksp):
    dc = DCache.create(wksp, "dc", mtu=128, depth=8)
    rng = np.random.default_rng(0)
    payloads = [rng.integers(0, 256, n, dtype=np.uint8) for n in (5, 128, 77)]
    chunks = np.array([dc.write(p) for p in payloads], dtype=np.uint32)
    szs = np.array([len(p) for p in payloads], dtype=np.uint16)
    mat = dc.read_batch(chunks, szs, width=128)
    assert mat.shape == (3, 128)
    for i, p in enumerate(payloads):
        assert np.array_equal(mat[i, : len(p)], p)
        assert not mat[i, len(p) :].any()


# ---------------------------------------------------------------------------
# fseq / fctl / cnc


def test_fseq_and_cr_avail(wksp):
    fs = FSeq.create(wksp, "fs", seq0=5)
    assert fs.query() == 5
    fs.update(42)
    assert fs.query() == 42
    fs.diag_add(0, 10)
    fs.diag_add(0, 5)
    assert fs.diag(0) == 15

    # producer at 42, consumer processed through 41, ring depth 16
    assert cr_avail(seq_prod=42, seq_cons_min=42, cr_max=16) == 16
    assert cr_avail(seq_prod=42, seq_cons_min=30, cr_max=16) == 4
    assert cr_avail(seq_prod=46, seq_cons_min=30, cr_max=16) == 0
    assert cr_avail(seq_prod=50, seq_cons_min=30, cr_max=16) == 0


def test_cnc(wksp):
    cnc = CNC.create(wksp, "cnc")
    assert cnc.signal_query() == 0  # BOOT
    cnc.signal(CNC_RUN)
    assert cnc.signal_query() == CNC_RUN
    cnc.heartbeat(12345)
    assert cnc.heartbeat_query() == 12345


# ---------------------------------------------------------------------------
# tcache


def test_tcache_basic(wksp):
    tc = TCache.create(wksp, "tc", depth=4)
    tags = np.array([1, 2, 3, 1, 2, 4], dtype=np.uint64)
    dup = tc.dedup(tags)
    assert list(dup) == [False, False, False, True, True, False]
    assert tc.query(4) and tc.query(1)
    assert not tc.query(99)


def test_tcache_eviction_oldest():
    wksp = Workspace(1 << 20)
    tc = TCache.create(wksp, "tc", depth=3)
    tc.dedup(np.array([10, 20, 30], dtype=np.uint64))
    # inserting a 4th unique evicts 10 (oldest)
    tc.dedup(np.array([40], dtype=np.uint64))
    assert not tc.query(10)
    assert tc.query(20) and tc.query(30) and tc.query(40)
    # re-inserting 10 is now "new"
    assert list(tc.dedup(np.array([10], dtype=np.uint64))) == [False]


def test_tcache_null_tag_passthrough(wksp):
    tc = TCache.create(wksp, "tc", depth=4)
    dup = tc.dedup(np.array([0, 0, 7, 7], dtype=np.uint64))
    assert list(dup) == [False, False, False, True]


def test_tcache_vs_python_model():
    """Randomized differential test vs an ordered-set model of the
    reference semantics (most-recent-depth-unique-tags)."""
    wksp = Workspace(1 << 20)
    depth = 16
    tc = TCache.create(wksp, "tc", depth=depth)
    rng = np.random.default_rng(7)
    model: list[int] = []  # insertion order, oldest first

    for _ in range(200):
        n = int(rng.integers(1, 20))
        tags = rng.integers(1, 40, n).astype(np.uint64)  # small space -> dups
        got = tc.dedup(tags)
        want = []
        for t in tags.tolist():
            if t in model:
                want.append(True)
            else:
                want.append(False)
                model.append(t)
                if len(model) > depth:
                    model.pop(0)
        assert list(got) == want


def test_tcache_dedup_journaled(wksp):
    """fdt_tcache_dedup_j: identical dedup semantics, plus every
    inserted tag journaled (in order, before the insert) with the
    overflow flag on capacity exhaustion."""
    tc = TCache.create(wksp, "tcj", depth=8)
    jnl = np.zeros(4 + 4, np.uint64)  # capacity 4 tags
    tags = np.array([5, 6, 5, 0, 7], dtype=np.uint64)
    dup = tc.dedup_j(tags, jnl)
    assert list(dup) == [False, False, True, False, False]
    assert int(jnl[2]) == 3 and int(jnl[3]) == 0
    assert jnl[4:7].tolist() == [5, 6, 7]  # inserted tags, in order
    # second batch: dups journal nothing; overflow sets the flag
    jnl[2] = 0
    dup = tc.dedup_j(np.array([5, 8, 9, 10, 11, 12], np.uint64), jnl)
    assert list(dup) == [True] + [False] * 5
    assert int(jnl[2]) == 4 and int(jnl[3]) == 1  # capped + flagged
    assert jnl[4:8].tolist() == [8, 9, 10, 11]
    # cache state matches the unjournaled call's semantics
    assert tc.query(12) and not tc.query(99)


def test_tcache_reset(wksp):
    tc = TCache.create(wksp, "tc", depth=4)
    tc.dedup(np.array([1, 2, 3], dtype=np.uint64))
    tc.reset()
    assert not tc.query(1)
    assert list(tc.dedup(np.array([1], dtype=np.uint64))) == [False]


# ---------------------------------------------------------------------------
# concurrency: real producer/consumer threads over one ring


def _producer(mc: MCache, fseqs: list[FSeq], n_msgs: int, depth: int):
    seq = 0
    while seq < n_msgs:
        cons_min = min(fs.query() for fs in fseqs)
        cr = cr_avail(seq, cons_min, depth)
        if cr == 0:
            continue
        for _ in range(min(cr, n_msgs - seq)):
            mc.publish(seq=seq, sig=seq * 3 + 1)
            seq += 1


def _consumer(mc: MCache, fseq: FSeq, n_msgs: int, out: list):
    seq = 0
    sigs = []
    while seq < n_msgs:
        frags, seq, ovr = mc.drain(seq, 256)
        assert ovr == 0, "reliable consumer must never be overrun"
        if len(frags):
            sigs.extend(frags["sig"].tolist())
            fseq.update(seq)
    out.extend(sigs)


@pytest.mark.parametrize("n_consumers", [1, 3])
def test_spmc_flow_controlled_stress(n_consumers):
    """Flow-controlled producer + reliable consumers: every message arrives
    exactly once, in order, at every consumer, with zero overruns."""
    wksp = Workspace(4 << 20)
    depth, n_msgs = 64, 20_000
    mc = MCache.create(wksp, "mc", depth=depth)
    fseqs = [FSeq.create(wksp, f"fs{i}") for i in range(n_consumers)]
    outs: list[list] = [[] for _ in range(n_consumers)]

    threads = [
        threading.Thread(target=_consumer, args=(mc, fseqs[i], n_msgs, outs[i]))
        for i in range(n_consumers)
    ]
    prod = threading.Thread(target=_producer, args=(mc, fseqs, n_msgs, depth))
    for t in threads:
        t.start()
    prod.start()
    prod.join(timeout=60)
    for t in threads:
        t.join(timeout=60)
    assert not prod.is_alive()
    expect = [s * 3 + 1 for s in range(n_msgs)]
    for out in outs:
        assert out == expect


def test_unreliable_consumer_overrun_counted():
    """An unreliable (non-flow-controlled) consumer that stalls gets lapped
    and the drain API reports exactly how many frags were lost."""
    wksp = Workspace(1 << 20)
    depth, n_msgs = 32, 500
    mc = MCache.create(wksp, "mc", depth=depth)
    for seq in range(n_msgs):
        mc.publish(seq=seq, sig=seq)
    got = 0
    seq = 0
    total_ovr = 0
    while seq < n_msgs:
        frags, seq, ovr = mc.drain(seq, 64)
        got += len(frags)
        total_ovr += ovr
    assert got + total_ovr == n_msgs
    assert total_ovr > 0


# ---------------------------------------------------------------------------
# workspace


def test_workspace_shm_named_roundtrip():
    w = Workspace(1 << 16, name="test_rt")
    try:
        mem = w.alloc("x", 1024)
        mem[:4] = [1, 2, 3, 4]
        assert np.array_equal(w.view("x")[:4], [1, 2, 3, 4])
    finally:
        w.unlink()


def test_workspace_full():
    w = Workspace(4096)
    with pytest.raises(MemoryError):
        w.alloc("big", 1 << 20)
