"""base58/base64/hex codecs, HMAC, SipHash-1-3, Murmur3-32 — known-answer
vectors (public canonical vectors: Bitcoin base58, RFC 4231 HMAC, the
standard SipHash key-00..0f/msg-0..i-1 convention, SMHasher murmur3)."""

import numpy as np
import pytest

from firedancer_tpu.ballet import base58 as B58
from firedancer_tpu.ballet import encodings as ENC
from firedancer_tpu.ballet import hmac as HM
from firedancer_tpu.ballet import murmur3 as MUR
from firedancer_tpu.ballet import siphash13 as SIP


def test_base58_known_vectors():
    assert B58.encode(b"") == ""
    assert B58.encode(b"\0" * 32) == "1" * 32
    assert B58.encode(b"Hello World!") == "2NEpo7TZRRrLZSi2U"
    assert (
        B58.encode(bytes.fromhex("0000287fb4cd")) == "11233QC4"
    )
    sys_prog = "11111111111111111111111111111111"
    assert B58.decode_32(sys_prog) == b"\0" * 32
    assert B58.encode_32(b"\0" * 32) == sys_prog


def test_base58_roundtrip_and_errors():
    rng = np.random.default_rng(0)
    for n in (1, 31, 32, 33, 64):
        data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        assert B58.decode(B58.encode(data), n) == data
    assert B58.decode("0OIl") is None  # chars outside the alphabet
    assert B58.decode_32("abc") is None  # wrong length
    s64 = B58.encode_64(bytes(range(64)))
    assert B58.decode_64(s64) == bytes(range(64))
    assert len(s64) <= B58.ENCODED_64_MAX


def test_base64_hex():
    data = bytes(range(256))
    assert ENC.base64_decode(ENC.base64_encode(data)) == data
    assert ENC.base64_decode("!!!!") is None
    assert ENC.hex_decode(ENC.hex_encode(data)) == data
    assert ENC.hex_decode("zz") is None


def test_hmac_rfc4231_case1():
    key = b"\x0b" * 20
    msg = b"Hi There"
    assert HM.hmac_sha256(key, msg) == bytes.fromhex(
        "b0344c61d8db38535ca8afceaf0bf12b881dc200c9833da726e9376c2e32cff7"
    )
    assert HM.hmac_sha512(key, msg) == bytes.fromhex(
        "87aa7cdea5ef619d4ff0b4241a1d6cb02379f4e2ce4ec2787ad0b30545e17cde"
        "daa833b7d6b8a702038b274eaea3f4e4be9d914eeb61f1702e696c203a126854"
    )


def test_hmac_rfc4231_case2():
    key = b"Jefe"
    msg = b"what do ya want for nothing?"
    assert HM.hmac_sha256(key, msg) == bytes.fromhex(
        "5bdcc146bf60754e6a042426089575c75a003f089d2739839dec58b964ec3843"
    )


def test_hmac_batch_matches_scalar():
    rng = np.random.default_rng(1)
    B, W = 5, 50
    keys = rng.integers(0, 256, (B, 16), np.uint8)
    msgs = rng.integers(0, 256, (B, W), np.uint8)
    lens = rng.integers(0, W + 1, B)
    out = HM.hmac_batch("sha256", keys, msgs, lens)
    for i in range(B):
        want = HM.hmac_sha256(bytes(keys[i]), bytes(msgs[i, : lens[i]]))
        assert bytes(out[i]) == want


def test_siphash13_vectors():
    # standard convention: key = 00..0f, msg = bytes 0..i-1
    k0 = 0x0706050403020100
    k1 = 0x0F0E0D0C0B0A0908
    want = [
        0xABAC0158050FC4DC,
        0xC9F49BF37D57CA93,
        0x82CB9B024DC7D44D,
        0x8BF80AB8E7DDF7FB,
        0xCF75576088D38328,
        0xDEF9D52F49533B67,
        0xC50D2B50C59F22A7,
    ]
    buf = bytes(range(len(want)))
    for i, w in enumerate(want):
        assert SIP.siphash13(k0, k1, buf[:i]) == w, i


def test_murmur3_vectors():
    assert MUR.murmur3_32(b"", 0) == 0
    assert MUR.murmur3_32(b"", 1) == 0x514E28B7
    assert MUR.murmur3_32(b"\xff\xff\xff\xff", 0) == 0x76293B50
    assert MUR.murmur3_32(b"!Ce\x87", 0) == 0xF55B516B


def test_murmur3_sbpf_syscall_hashes():
    # the actual use: Solana sBPF syscall-name hashes (seed 0); these are
    # on-chain consensus values, and the odd lengths exercise every tail
    # path of the x86_32 variant
    cases = {
        b"abort": 0xB6FC1A11,
        b"sol_panic_": 0x686093BB,
        b"sol_log_": 0x207559BD,
        b"sol_log_64_": 0x5C2A3178,
        b"sol_log_compute_units_": 0x52BA5096,
        b"sol_sha256": 0x11F49D86,
        b"sol_keccak256": 0xD7793ABB,
        b"sol_get_processed_sibling_instruction": 0xADB8EFC8,
    }
    for name, want in cases.items():
        assert MUR.murmur3_32(name, 0) == want, name
