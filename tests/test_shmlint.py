"""fdtshm (ISSUE 18): the C11 shared-memory effects analyzer.

Four layers under test:

  1. cparse statement parser on adversarial C — nested macros, do/while,
     ternary-embedded stores, compound literals, literal-aware brace
     matching — the foundation the effects extraction walks.
  2. Effects extraction: atomic builtins with their memory_order, plain
     stores/loads, word classification, loop-path tracking.
  3. The fdt_tango-vs-RingHook differential: the effects extracted from
     the C ring primitives match the `_MC` micro-step decomposition
     (analysis/sched.py RingHook, installed as tango.rings._MC)
     access-for-access and order-for-order — the model checker provably
     models what the C does.
  4. The contract rules on the shipped surface + pinned mutant flips:
     the fixed true positives (fdt_stem BJ_COMPLETED release,
     fdt_trace hist/clock atomics, fdt_net per-round credit re-read)
     stay fixed — reverting any one of them trips its rule again.

The known-bad corpus detection matrix lives in test_fdtlint.py
(BAD_FIXTURES); here we assert the suppression side (shm_good.c) and
the per-rule finding shapes.
"""

from __future__ import annotations

import re
from pathlib import Path

import pytest

from firedancer_tpu.analysis import cparse, engine, shmcontract, shmlint

REPO = Path(__file__).resolve().parent.parent
NATIVE = REPO / "firedancer_tpu" / "tango" / "native"
CORPUS = REPO / "tests" / "fixtures" / "lint_corpus"
SCHED = REPO / "firedancer_tpu" / "analysis" / "sched.py"


# ---------------------------------------------------------------------------
# 1. statement parser on adversarial C


ADVERSARIAL_C = r"""
#define EMIT( x ) do { buf[ n++ ] = ( x ); } while( 0 )
#define WRAP( a, b ) \
  EMIT( ( a ) + ( b ) )

struct pt { int x; int y[ 2 ]; };

static int fdt_adversarial( int * buf, int q ) {
  int n = 0;
  do {
    EMIT( WRAP( 1, 2 ) );
  } while( n < 3 );
  int x = q > 1 ? ( buf[ 0 ] = 7 ) : ( buf[ 1 ] = 9 );
  struct pt p = (struct pt){ .x = 1, .y = { 2, 3 } };
  char * s = "unbalanced ) } in a literal (";
  for( int i = 0; i < p.x; i++ ) buf[ i ] = i + s[ 0 ];
  if( x ) { n++; } else n--;
  switch( x ) { case 1: n = 2; break; default: n = 3; }
  return n;
}
"""


def _flatten(stmts):
    for st in stmts:
        yield st
        yield from _flatten(st.body)
        yield from _flatten(st.orelse)


def test_parser_adversarial_structure():
    fns = cparse.parse_c_functions(ADVERSARIAL_C)
    assert [f.name for f in fns] == ["fdt_adversarial"]
    (fn,) = fns
    flat = list(_flatten(fn.body))
    kinds = [st.kind for st in fn.body]
    # do/while, the ternary decl, compound literal decl, string decl,
    # for, if, switch, return — all at top level
    assert kinds.count("loop") == 2  # do-while + for
    loop_kinds = [st.loop_kind for st in fn.body if st.kind == "loop"]
    assert loop_kinds == ["do", "for"]
    assert any(st.kind == "if" for st in fn.body)
    assert any(st.kind == "switch" for st in fn.body)
    # the do body holds the macro invocation as an expr statement
    do_stmt = next(st for st in fn.body if st.loop_kind == "do")
    assert any("EMIT" in st.text for st in do_stmt.body)
    # do/while condition captured in the loop header text
    assert "n < 3" in do_stmt.text
    # unbraced for body still nests
    for_stmt = next(st for st in fn.body if st.loop_kind == "for")
    assert len(for_stmt.body) == 1 and "buf[ i ]" in for_stmt.body[0].text
    # if/else: both branches present
    if_stmt = next(st for st in fn.body if st.kind == "if")
    assert if_stmt.body and if_stmt.orelse
    # case labels are skipped, their statements kept
    sw = next(st for st in fn.body if st.kind == "switch")
    assert any("n = 2" in st.text for st in _flatten(sw.body))
    # nothing in the flattened tree kept a preprocessor line
    assert not any(st.text.startswith("#") for st in flat)


def test_parser_skips_prototypes_and_matches_literal_braces():
    src = (
        "int fdt_decl( int a );\n"
        "static int helper( char c ) { return c == '}' ? 1 : 0; }\n"
        'int fdt_body( void ) { return helper( \'{\' ) + sizeof ")"; }\n'
    )
    fns = cparse.parse_c_functions(src)
    assert [f.name for f in fns] == ["helper", "fdt_body"]
    assert fns[0].static and not fns[1].static


def test_find_calls_skips_keywords_and_nests():
    calls = cparse.find_calls(
        "if( fdt_a( fdt_b( x ), y ) ) while( fdt_c() ) fdt_d( 0 );"
    )
    assert [c[0] for c in calls] == ["fdt_a", "fdt_b", "fdt_c", "fdt_d"]
    assert cparse.split_args("fdt_b( x ), y") == ["fdt_b( x )", "y"]


# ---------------------------------------------------------------------------
# 2. effects extraction


def _eff(src: str, file: str, fn: str):
    return shmlint.analyze_source(src, file)[fn]


def test_atomic_orders_and_classification():
    src = """
void fdt_mcache_probe( fdt_mcache_hdr_t * h ) {
  uint64_t v = atomic_load_explicit( &h->seq_prod, memory_order_acquire );
  atomic_store_explicit( &h->seq_prod, v, memory_order_release );
  __atomic_fetch_add( &h->seq_prod, 1UL, __ATOMIC_RELAXED );
  atomic_thread_fence( memory_order_seq_cst );
}
"""
    eff = _eff(src, "fdt_tango.c", "fdt_mcache_probe")
    got = [(e.kind, e.cls, e.order) for e in eff]
    assert got == [
        ("load", "mcache.seq_prod", "acquire"),
        ("store", "mcache.seq_prod", "release"),
        ("rmw", "mcache.seq_prod", "relaxed"),
        ("fence", "", "seq_cst"),
    ]


def test_plain_store_forms_and_loop_paths():
    src = """
void fdt_mcache_probe( uint64_t * x, fdt_frag_t * f ) {
  f->sig = 1;
  f->sz += 2;
  f->ctl++;
  for( int i = 0; i < 4; i++ ) {
    while( f->chunk ) {
      f->tsorig = 0;
    }
  }
}
"""
    eff = _eff(src, "fdt_tango.c", "fdt_mcache_probe")
    stores = [(e.expr, e.kind, e.loops) for e in eff if e.cls == "mcache.line"]
    assert ("f->sig", "store", ()) in stores
    assert ("f->sz", "store", ()) in stores
    assert ("f->ctl", "store", ()) in stores
    # the while-condition load sits inside BOTH loops (headers re-run
    # per iteration); the innermost store carries the full loop path
    cond = next(e for e in eff if e.expr == "->chunk")
    assert len(cond.loops) == 2
    inner = next(e for e in eff if e.expr == "f->tsorig")
    assert inner.loops == cond.loops


def test_ternary_embedded_store_is_seen():
    eff = _eff(
        "void fdt_t( uint64_t * h, int x ) {\n"
        "  int y = x ? ( h[ 0 ] = 1 ) : ( h[ 1 ] = 2 );\n"
        "}\n",
        "fdt_trace.c",
        "fdt_t",
    )
    assert [(e.kind, e.cls) for e in eff if e.cls] == [
        ("store", "trace.hist"),
        ("store", "trace.hist"),
    ]


# ---------------------------------------------------------------------------
# 3. the fdt_tango-vs-_MC differential


def _c_effects():
    return shmlint.analyze_file(NATIVE / "fdt_tango.c")


def _c_field(e: shmlint.Effect) -> str:
    if e.cls.startswith("fseq.") and "diag" in e.expr:
        return "diag"
    m = re.search(r"->\s*(\w+)", e.expr)
    assert m, e.expr
    return m.group(1)


def _c_rw(effects) -> tuple[list, list]:
    """Classified ring accesses of one C primitive as the differential's
    (writes, reads) field sequences.  An rmw is a write (its read half
    is the same word, same instruction — not a separate micro-step)."""
    writes, reads = [], []
    for e in effects:
        if not (e.cls.startswith("mcache.") or e.cls.startswith("fseq.")):
            continue
        obj = "mc" if e.cls.startswith("mcache.") else "fs"
        if e.kind in ("store", "rmw", "cas"):
            writes.append(("w", obj, _c_field(e)))
        elif e.kind == "load":
            reads.append(("r", obj, _c_field(e)))
    return writes, reads


def test_differential_tango_matches_mc_decomposition():
    """Access-for-access: for every RingHook micro-step method, the
    shared words the Python model writes are EXACTLY the words the C
    primitive writes, in the same order; for read primitives the read
    sequences match too.  The model may carry observability-only
    pre-reads (fseq_update's notify read), so for write primitives the
    C side's classified reads must be a subset of the model's."""
    mc = shmcontract.ringhook_accesses(SCHED)
    ceff = _c_effects()
    assert set(mc) == set(shmcontract.RINGHOOK_METHODS), sorted(mc)
    for method, cname in shmcontract.RINGHOOK_METHODS.items():
        writes, reads = _c_rw(ceff[cname])
        py = mc[method]
        py_writes = [a for a in py if a[0] == "w"]
        py_reads = [a for a in py if a[0] == "r"]
        assert writes == py_writes, (
            f"{method} vs {cname}: C writes {writes}, model writes {py_writes}"
        )
        if py_writes:
            # write primitive: C must not read ring words the model
            # doesn't know about
            assert set(reads) <= set(py_reads), (method, reads, py_reads)
        else:
            assert reads == py_reads, (
                f"{method} vs {cname}: C reads {reads}, model reads {py_reads}"
            )


def test_differential_order_for_order():
    """The C11 orders of fdt_tango.c's ring primitives, pinned as the
    exact classified-effect sequences.  This is the ordering contract
    the RingHook micro-steps (and fdtmc's interleaving exploration)
    assume: change the C and this fails until the model is re-derived."""
    ceff = _c_effects()

    def seq(fn):
        return [
            (e.kind, e.cls, e.order)
            for e in ceff[fn]
            if e.cls.startswith(("mcache.", "fseq.")) or e.kind == "fence"
        ]

    line = ("store", "mcache.line", "plain")
    assert seq("fdt_mcache_publish") == [
        ("store", "mcache.seq", "relaxed"),  # invalidate
        ("fence", "", "release"),
        line, line, line, line, line, line,  # sig/chunk/sz/ctl/tsorig/tspub
        ("fence", "", "release"),
        ("store", "mcache.seq", "release"),  # commit
        ("store", "mcache.seq_prod", "release"),
    ]
    rd = ("load", "mcache.line", "plain")
    assert seq("fdt_mcache_poll") == [
        ("load", "mcache.seq", "acquire"),
        rd, rd, rd, rd, rd, rd,
        ("fence", "", "acquire"),
        ("load", "mcache.seq", "acquire"),  # seqlock re-check
    ]
    assert seq("fdt_mcache_seq_query") == [
        ("load", "mcache.seq_prod", "acquire")
    ]
    assert seq("fdt_mcache_seq_advance") == [
        ("store", "mcache.seq_prod", "release")
    ]
    assert seq("fdt_fseq_query") == [("load", "fseq.seq", "acquire")]
    assert seq("fdt_fseq_update") == [("store", "fseq.seq", "release")]
    assert seq("fdt_fseq_diag_query") == [("load", "fseq.diag", "relaxed")]
    assert seq("fdt_fseq_diag_add") == [("rmw", "fseq.diag", "relaxed")]
    # pure credit arithmetic: no shared access on either side
    assert seq("fdt_fctl_cr_avail") == []


# ---------------------------------------------------------------------------
# 4. contract rules: shipped surface clean, suppression, mutant flips


def test_shipped_native_surface_is_clean():
    findings = []
    for p in sorted(NATIVE.glob("*.c")):
        findings += shmlint.check_native_c_file(p, rel=REPO)
    assert findings == [], "\n".join(str(f) for f in findings)


def test_shm_good_pragmas_suppress_and_strip_restores():
    src = (CORPUS / "shm_good.c").read_text()
    assert shmlint.check_source(src, "shm_good.c", "shm_good.c") == []
    stripped = "\n".join(
        ln for ln in src.splitlines() if "fdtlint:" not in ln
    )
    rules = {
        f.rule for f in shmlint.check_source(stripped, "shm_good.c", "shm_good.c")
    }
    assert rules == {
        "shm-publish-release",
        "shm-single-writer",
        "shm-stale-credit",
        "shm-journal-arm",
        "shm-epoch-check",
    }, sorted(rules)


def _mutate_and_check(path: Path, pattern: str, repl: str, rule: str):
    src = path.read_text()
    mutant = re.sub(pattern, repl, src, count=1, flags=re.S)
    assert mutant != src, f"mutation pattern matched nothing in {path.name}"
    findings = shmlint.check_source(mutant, path.name, path.name)
    assert any(f.rule == rule for f in findings), (
        f"reverting the {path.name} fix no longer trips {rule}: "
        + "\n".join(str(f) for f in findings)
    )


def test_regression_bank_completed_mark_needs_release():
    """PINNED (real ordering bug, fixed this PR): fdt_bank_pipeline's
    completed-seq mark was a plain store; a recovery process could read
    the new mark without the slot/journal stores it covers.  Reverting
    to the plain store must trip shm-publish-release forever."""
    _mutate_and_check(
        NATIVE / "fdt_stem.c",
        r"__atomic_store_n\( &jw\[ BJ_COMPLETED \], mb_tag \+ 1UL,\s*"
        r"__ATOMIC_RELEASE \)",
        "jw[ BJ_COMPLETED ] = mb_tag + 1UL",
        "shm-publish-release",
    )


def test_regression_trace_hist_words_stay_atomic():
    _mutate_and_check(
        NATIVE / "fdt_trace.c",
        r"__atomic_store_n\( &h\[ b \],.*?__ATOMIC_RELAXED \)",
        "h[ b ] += 1UL",
        "shm-publish-release",
    )


def test_regression_net_rx_credit_stays_in_loop():
    """Reverting fdt_net_rx to a hoisted credit snapshot (no re-read
    inside the recvmmsg round loop) must trip shm-stale-credit."""
    _mutate_and_check(
        NATIVE / "fdt_net.c",
        r"int64_t cr = fdt_stem_out_cr\( ob \);",
        "int64_t cr = burst;",
        "shm-stale-credit",
    )


# ---------------------------------------------------------------------------
# 5. coverage floor: a new .c cannot silently skip the scan


@pytest.fixture(scope="module")
def repo_report():
    return engine.run_repo()


def test_native_files_coverage_floor(repo_report):
    cov = repo_report.coverage
    on_disk = sorted(
        p.relative_to(REPO).as_posix() for p in NATIVE.glob("*.c")
    )
    assert cov["native_c_files"] == on_disk
    # the shm analyzer must actually SEE the surface: every native file
    # parses to at least one function, and the aggregate counts sit
    # above a floor a silent parser regression would fall through
    for p in sorted(NATIVE.glob("*.c")):
        assert shmlint.analyze_file(p), f"{p.name}: no functions parsed"
    assert cov["shm_functions"] >= 140, cov["shm_functions"]
    assert cov["shm_effects"] >= 550, cov["shm_effects"]


def test_repo_report_has_no_shm_findings(repo_report):
    assert not [
        f for f in repo_report.findings if f.rule.startswith("shm-")
    ]
