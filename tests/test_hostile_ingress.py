"""Hostile-ingress hardening (ISSUE 13): admission control, stake-
weighted QoS, SLO-driven load shedding, injected-attack faults, and the
verify-layer poison-resistance the adversary harness leans on.

Fast section: pure policy units (waltz/admission.py), the quic tile's
gate/preemption/egress metering with a stub ctx, faultinj's injected
kinds + the cross-process fired-flag fold, fdtincident shed
classification, config plumbing, and the wire-edge pre-allocation gate.

Slow section: a laced verify batch (non-canonical sigs + small-order
pubkeys must die at verify without poisoning neighbors) and a bounded
seeded adversary smoke — the same invariant set checkall's adversary
stage runs at full scale.
"""

import json
import types

import numpy as np
import pytest

from firedancer_tpu.disco.faultinj import Fault, FaultInjector
from firedancer_tpu.disco.metrics import Metrics
from firedancer_tpu.disco.slo import SloConfig, SloEngine, SloStatus
from firedancer_tpu.waltz import admission as ADM
from firedancer_tpu.waltz.admission import (
    TICKS_PER_S,
    AdmissionConfig,
    ConnAdmission,
    LoadShedder,
    StakeTable,
    TokenBucket,
)

S = TICKS_PER_S


# ---------------------------------------------------------------------------
# token bucket (tick domain)


def test_token_bucket_tick_domain():
    b = TokenBucket(rate_per_s=10, burst=4)
    # full burst up front, then empty
    assert b.take(now=0, n=6) == 4
    assert b.take(now=0, n=1) == 0
    # refill is exact integer math: 10/s -> one token per S//10 ticks
    assert b.take(now=S // 10, n=2) == 1
    assert b.take(now=S // 10, n=1) == 0
    # a long gap refills to the burst cap, never beyond
    assert b.take(now=100 * S, n=100) == 4
    # rate 0 disables (always admits)
    assert TokenBucket(0, 1).take(now=0, n=999) == 999


def test_token_bucket_never_reads_clock():
    """The tick-domain contract the fdtlint hot-path-clock rule
    polices: every admission-policy method takes `now` from the caller
    (no time.* source inside waltz/admission.py at all)."""
    import inspect

    src = inspect.getsource(ADM)
    assert "import time" not in src
    assert "time.monotonic" not in src


# ---------------------------------------------------------------------------
# connection admission


def _adm(**kw) -> ConnAdmission:
    base = dict(
        max_conns=4, max_conns_per_source=2,
        handshake_rate=2, handshake_burst=2,
        txn_rate=5, txn_burst=3,
    )
    base.update(kw)
    return ConnAdmission(AdmissionConfig(**base))


def test_conn_admission_caps_and_reasons():
    a = _adm()
    # handshake-rate bucket: burst of 2, then the rate reason
    assert a.admit_handshake(("1.1.1.1", 1), now=0) is None
    assert a.admit_handshake(("1.1.1.2", 1), now=0) is None
    assert a.admit_handshake(("1.1.1.3", 1), now=0) == "drop_handshake_rate"
    # per-source cap: one IP across ephemeral ports is ONE source
    now = S  # refill
    for i in range(2):
        assert a.admit_conn(("9.9.9.9", 1000 + i), now) is None
        a.conn_opened(bytes([i]), ("9.9.9.9", 1000 + i), now)
    assert a.admit_conn(("9.9.9.9", 3000), now) == "drop_source_cap"
    # global cap
    for i in range(2):
        a.conn_opened(bytes([16 + i]), (f"8.8.8.{i}", 1), now)
    assert a.admit_conn(("7.7.7.7", 1), now) == "drop_conn_cap"
    # releasing frees both the global slot and the source slot
    a.conn_released(bytes([0]))
    a.conn_released(bytes([16]))
    assert a.admit_conn(("9.9.9.9", 3000), now) is None


def test_conn_admission_emergency_level_refuses_unstaked():
    stakes = StakeTable({b"1.2.3.4:5": 10_000})
    a = ConnAdmission(AdmissionConfig(), stakes)
    a.level = 3  # emergency staked-only (mirrored in by the tile)
    assert a.admit_handshake(("6.6.6.6", 1), now=0) == "drop_emergency"
    assert a.admit_handshake(("1.2.3.4", 5), now=0) is None


def test_txn_rate_bucket_and_high_stake_exemption():
    stakes = StakeTable({b"whale": 1_000_000}, low_stake=1000)
    a = ConnAdmission(
        AdmissionConfig(txn_rate=5, txn_burst=3), stakes
    )
    # unstaked flow: burst 3 then rate-limited
    assert a.admit_txns(b"k1", b"nobody", now=0, n=5) == 3
    assert a.admit_txns(b"k1", b"nobody", now=0, n=1) == 0
    # high-stake identity is exempt — priority is the point
    assert a.admit_txns(b"k2", b"whale", now=0, n=500) == 500


def test_idle_and_slow_loris_sweep():
    a = ConnAdmission(
        AdmissionConfig(idle_timeout_s=1.0, handshake_timeout_s=0.5)
    )
    est = types.SimpleNamespace(
        scid=b"A", established=True, last_rx_tick=1
    )
    loris = types.SimpleNamespace(
        scid=b"B", established=False, last_rx_tick=0
    )
    server = types.SimpleNamespace(
        by_addr={("1.1.1.1", 1): est, ("2.2.2.2", 2): loris}
    )
    a.conn_opened(b"A", ("1.1.1.1", 1), now=1)
    a.conn_opened(b"B", ("2.2.2.2", 2), now=1)
    # before any deadline: nothing
    idle, hs = a.sweep(server, now=int(0.2 * S))
    assert idle == [] and hs == []
    # past the handshake deadline the un-established conn is a loris
    # victim even though it stays "active"
    loris.last_rx_tick = int(0.6 * S)
    idle, hs = a.sweep(server, now=int(0.7 * S))
    assert hs == [("2.2.2.2", 2)] and idle == []
    # past idle_timeout the silent established conn is idle churn
    idle, hs = a.sweep(server, now=int(1.5 * S))
    assert ("1.1.1.1", 1) in idle


# ---------------------------------------------------------------------------
# load shedder


def test_load_shedder_hysteresis_and_commanded_floor():
    cfg = AdmissionConfig(
        shed_hi=0.75, shed_lo=0.25, shed_cooldown_s=1.0, shed_dwell_s=0.1
    )
    sh = LoadShedder(cfg)
    D = int(0.1 * S)
    # escalation: one level per DWELL while hot (walks the ladder
    # across dwells — a sub-dwell transient costs at most one level)
    assert sh.update(0, 0.9) == 1
    assert sh.update(1, 0.9) == 1  # same dwell: paced, no jump
    assert sh.update(D, 0.9) == 2
    assert sh.update(2 * D, 0.9) == 3
    assert sh.update(3 * D, 0.9) == 3  # clamped at MAX_LEVEL
    # mid-band occupancy holds the level (no flapping)
    assert sh.update(3 * D, 0.5) == 3
    # de-escalation needs calm SUSTAINED for the cooldown
    assert sh.update(1 * S, 0.1) == 3
    assert sh.update(int(1.5 * S), 0.1) == 3
    assert sh.update(int(2.1 * S), 0.1) == 2
    # the SLO engine's commanded level is a FLOOR: raises, never lowers
    assert sh.update(int(2.2 * S), 0.1, commanded=3) == 3
    lvl_before = sh.level
    assert sh.update(int(2.3 * S), 0.1, commanded=0) == lvl_before
    assert sh.transitions >= 5


def test_shed_level_gates_by_class():
    assert LoadShedder.admits(ADM.CLASS_UNSTAKED, 0)
    assert not LoadShedder.admits(ADM.CLASS_UNSTAKED, 1)
    assert LoadShedder.admits(ADM.CLASS_LOW, 1)
    assert not LoadShedder.admits(ADM.CLASS_LOW, 2)
    assert LoadShedder.admits(ADM.CLASS_HI, 3)


# ---------------------------------------------------------------------------
# stake table


def test_stake_table_config_and_classes():
    t = StakeTable.from_config(
        {"0x0a0b": 500, "1.2.3.4:5": 70_000}, low_stake=1000
    )
    assert t.weight(b"\x0a\x0b") == 500
    assert t.cls_of(b"\x0a\x0b") == ADM.CLASS_LOW
    assert t.cls_of(b"1.2.3.4:5") == ADM.CLASS_HI
    assert t.cls_of(b"unknown") == ADM.CLASS_UNSTAKED
    assert t.cls_of(None) == ADM.CLASS_UNSTAKED


def test_stake_table_synthetic_deterministic():
    a = StakeTable.synthetic(12, seed=5)
    b = StakeTable.synthetic(12, seed=5)
    c = StakeTable.synthetic(12, seed=6)
    assert a.stakes == b.stakes
    assert a.stakes != c.stakes
    assert all(w > 0 for w in a.stakes.values())


# ---------------------------------------------------------------------------
# SLO -> commanded shed level


def _status(name, burn_fast=0.0, breached=False):
    return SloStatus(
        name=name, threshold=0.0, burn_fast=burn_fast, breached=breached
    )


def test_slo_recommended_shed_level():
    eng = SloEngine(SloConfig(e2e_p99_us=60_000, burn_fast=8.0), {})
    eng._last = [_status("e2e_p99_us")]
    assert eng.recommended_shed_level() == 0
    eng._last = [_status("e2e_p99_us", burn_fast=1.5)]
    assert eng.recommended_shed_level() == 1
    eng._last = [_status("e2e_p99_us", burn_fast=9.0)]
    assert eng.recommended_shed_level() == 2
    eng._last = [_status("e2e_p99_us", breached=True)]
    assert eng.recommended_shed_level() == 3
    # drop_rate_max AND landed_tps_min are EXCLUDED: shedding raises
    # the drop rate and lowers landed throughput by design; feeding
    # either back would latch the shedder at max forever (a benign
    # traffic lull must never blackhole unstaked ingress)
    eng._last = [_status("drop_rate_max", burn_fast=99.0, breached=True)]
    assert eng.recommended_shed_level() == 0
    eng._last = [_status("landed_tps_min", burn_fast=99.0, breached=True)]
    assert eng.recommended_shed_level() == 0


# ---------------------------------------------------------------------------
# fdtincident: shed-bundle classification


def _shed_bundle(level, fired=(), slo_status=()):
    return {
        "id": "t-0001-shed",
        "trigger": {
            "kind": "shed", "tile": "quic", "detail": {"level": level},
        },
        "faultinj": {"seed": 1, "fired": [list(e) for e in fired]},
        "slo": {"status": [s.to_dict() for s in slo_status]},
        "timeline": {},
    }


def test_fdtincident_classifies_shed_bundles():
    from scripts.fdtincident import classify_bundle

    # backed by a scripted flood: expected, correctly labeled
    r = classify_bundle(
        _shed_bundle(2, fired=[("quic", "flood", 100, (64, "garbage"))])
    )
    assert r["class"] == "load-shed:L2" and r["explained"]
    # backed by a burning SLO (the engine's commanded floor)
    r = classify_bundle(
        _shed_bundle(1, slo_status=[_status("e2e_p99_us", burn_fast=2.0)])
    )
    assert r["class"] == "load-shed:L1" and r["explained"]
    # nothing scripted, nothing burning: something unscripted is
    # flooding — must demand investigation
    r = classify_bundle(_shed_bundle(3))
    assert r["class"] == "unexplained-shed:L3" and not r["explained"]


# ---------------------------------------------------------------------------
# faultinj: injected-traffic kinds


def test_flood_fault_fires_once_and_is_canonical():
    faults = [
        Fault("quic", "flood", at=3, count=16, link="garbage"),
        Fault("quic", "conn_churn", at=5, count=8),
    ]
    inj = FaultInjector(seed=9, faults=faults)
    tf = inj.view("quic")
    for _ in range(10):
        tf.tick(None)
    got = tf.take_injected()
    assert [(k, c, p) for _, k, c, p in got] == [
        ("flood", 16, "garbage"), ("conn_churn", 8, None),
    ]
    assert tf.take_injected() == []  # drained exactly once
    for _ in range(10):
        tf.tick(None)
    assert tf.take_injected() == []  # fired flags are durable
    # canonical record: same seed + schedule -> equal fired() lists
    inj2 = FaultInjector(seed=9, faults=[
        Fault("quic", "flood", at=3, count=16, link="garbage"),
        Fault("quic", "conn_churn", at=5, count=8),
    ])
    tf2 = inj2.view("quic")
    for _ in range(10):
        tf2.tick(None)
    assert inj.fired() == inj2.fired()
    assert {e[1] for e in inj.fired()} == {"flood", "conn_churn"}


def test_flood_fault_traced_in_timeline_codes():
    from firedancer_tpu.disco.trace import FAULT_CODES, FAULT_NAMES

    assert "flood" in FAULT_CODES and "conn_churn" in FAULT_CODES
    assert FAULT_NAMES[FAULT_CODES["flood"]] == "flood"


def test_fold_shm_fired_reconstructs_parent_record():
    """Process-runtime bridge: a child's durable fired flags rebuild
    the parent's canonical events for every tick-domain kind, so
    bundles classify identically under both runtimes."""
    sched = lambda: [  # noqa: E731 — same schedule on both sides
        Fault("quic", "flood", at=2, count=12, link="dup"),
        Fault("quic", "conn_churn", at=4, count=6),
        Fault("quic", "backpressure", at=6, count=3),
    ]
    child = FaultInjector(seed=3, faults=sched())
    tf = child.view("quic")
    shm = np.zeros(64, np.uint8)
    tf.bind_shm(shm)
    for _ in range(8):
        tf.tick(None)
    tf.take_injected()
    assert len(child.events) == 3

    parent = FaultInjector(seed=3, faults=sched())
    assert parent.fired() == []  # process isolation: no parent events
    parent.fold_shm_fired("quic", shm)
    assert parent.fired() == child.fired()
    # idempotent: folding again does not duplicate
    parent.fold_shm_fired("quic", shm)
    assert parent.fired() == child.fired()


# ---------------------------------------------------------------------------
# quic tile: gate ledger, stake preemption, egress metering


def _tile_ctx(tile):
    mem = np.zeros(
        Metrics.footprint(tile.schema.with_base()), dtype=np.uint8
    )
    return types.SimpleNamespace(
        metrics=Metrics(mem, tile.schema.with_base())
    )


def _mk_tile(**adm_kw):
    from firedancer_tpu.tiles.quic import QuicIngressTile

    stakes = StakeTable(
        {b"staker": 50_000, b"minnow": 10}, low_stake=1000
    )
    qt = QuicIngressTile(
        b"\x07" * 32, via_net=True,
        admission=AdmissionConfig(**adm_kw), stakes=stakes,
    )
    qt.on_boot(None)  # via_net: no sockets; wires admission/shedder
    return qt


def test_backlog_preemption_staked_evicts_unstaked():
    qt = _mk_tile(backlog_cap=4)
    ctx = _tile_ctx(qt)
    for i in range(4):
        assert qt._enqueue(ctx, b"u%d" % i, ADM.CLASS_UNSTAKED)
    # at capacity: an arriving staked txn evicts the OLDEST unstaked
    assert qt._enqueue(ctx, b"hi", ADM.CLASS_HI)
    assert ctx.metrics.counter("shed_backlog") == 1
    assert list(qt._backlogs[ADM.CLASS_HI]) == [b"hi"]
    assert list(qt._backlogs[ADM.CLASS_UNSTAKED]) == [b"u1", b"u2", b"u3"]
    # same-or-lower class incoming at capacity is the refused side
    assert not qt._enqueue(ctx, b"u9", ADM.CLASS_UNSTAKED)
    assert ctx.metrics.counter("shed_backlog") == 2


def test_gate_ledger_closes_per_call():
    qt = _mk_tile(txn_rate=5, txn_burst=2)
    ctx = _tile_ctx(qt)
    admitted = [[] for _ in range(3)]
    # unstaked source under L1 shed: everything gate-shed
    qt.shedder.level = 1
    qt._gate_raws(ctx, [b"a", b"b"], b"nobody", b"k0", 0, admitted)
    # staked source: rate-exempt? no — only CLASS_HI is exempt; this
    # one IS high-stake so all admit
    qt._gate_raws(ctx, [b"c"] * 3, b"staker", b"k1", 0, admitted)
    # low-stake source at L1 passes the level gate but hits the rate
    # bucket (burst 2)
    qt._gate_raws(ctx, [b"d"] * 4, b"minnow", b"k2", 0, admitted)
    m = ctx.metrics
    offered = m.counter("gate_txns")
    accounted = (
        m.counter("admit_staked") + m.counter("admit_unstaked")
        + m.counter("drop_txn_rate") + m.counter("shed_unstaked")
        + m.counter("shed_lowstake")
    )
    assert offered == 9 and accounted == 9
    assert m.counter("shed_unstaked") == 2
    assert m.counter("drop_txn_rate") == 2
    assert m.counter("admit_staked") == 5  # 3 whale + 2 minnow
    assert len(admitted[ADM.CLASS_HI]) == 3
    assert len(admitted[ADM.CLASS_LOW]) == 2


def test_dup_wave_injects_exactly_scheduled_count():
    """A dup wave replays exactly its scheduled count from the
    recent-admit pool — it must not ALSO fall through to the malformed
    branch and double-inject (canonical record would lie)."""
    qt = _mk_tile()
    ctx = _tile_ctx(qt)
    qt._recent_raws.extend([b"r1", b"r2"])
    h = np.arange(5, dtype=np.uint64)
    qt._inject_txns(ctx, seed=7, fi=0, h=h, prof="dup", now=0)
    assert ctx.metrics.counter("adv_injected") == 5
    assert ctx.metrics.counter("gate_txns") == 5
    # empty pool degrades to malformed spam, still exactly the count
    qt2 = _mk_tile()
    ctx2 = _tile_ctx(qt2)
    qt2._inject_txns(ctx2, seed=7, fi=0, h=h, prof="dup", now=0)
    assert ctx2.metrics.counter("adv_injected") == 5


def test_tx_eagain_tail_is_metered():
    """ISSUE 13 satellite: the egress burst tail dropped on EAGAIN was
    a silent `break` — it must be a metered drop with a monitor NOTE."""
    qt = _mk_tile()
    qt.via_net = False  # exercise the native-send branch
    ctx = _tile_ctx(qt)
    qt._send_burst_native = lambda pkts: max(len(pkts) - 3, 0)  # EAGAIN
    qt._tx(ctx, [(b"d%d" % i, ("127.0.0.1", 9)) for i in range(8)])
    assert ctx.metrics.counter("tx_dgrams") == 5
    assert ctx.metrics.counter("tx_eagain_drops") == 3

    from firedancer_tpu.app.monitor import Monitor

    snap = {
        "quic": {
            "signal": "RUN", "heartbeat": 1, "stale": False,
            "counters": {
                c: ctx.metrics.counter(c)
                for c in qt.schema.with_base().counters
            },
        }
    }
    mon = object.__new__(Monitor)  # alarms() is pure over snap
    notes = mon.alarms(snap)
    assert any(
        "tx_eagain" in n or "EAGAIN" in n for n in notes
    ), notes


def test_monitor_surfaces_shed_level_and_ingress_row():
    from firedancer_tpu.app.monitor import Monitor

    counters = {
        "shed_level": 3, "shed_transitions": 4, "gate_txns": 100,
        "admit_staked": 60, "admit_unstaked": 0, "shed_unstaked": 30,
        "shed_lowstake": 5, "shed_backlog": 5, "drop_txn_rate": 0,
        "drop_conn_cap": 1, "drop_source_cap": 0, "drop_emergency": 2,
        "drop_handshake_rate": 7, "conns_evicted_idle": 1,
        "conns_evicted_handshake": 2, "in_frags": 0, "out_frags": 60,
    }
    snap = {
        "quic": {
            "signal": "RUN", "heartbeat": 1, "stale": False,
            "counters": counters,
        }
    }
    mon = object.__new__(Monitor)
    alarms = mon.alarms(snap)
    # emergency staked-only is an ALARM, not a note
    assert any(
        a.startswith("ALARM") and "staked-only" in a for a in alarms
    ), alarms
    out = mon.render(None, snap, 1.0)
    assert "ingress:" in out and "level=3" in out


# ---------------------------------------------------------------------------
# attack-path crypto cost (the quic-loop-under-flood fix)


def test_ghash_fast_table_matches_bitserial_reference():
    """The subset-xor GHASH table build (the 75 ms -> 1 ms AesGcm ctor
    fix that un-wedged the quic loop under handshake flood) must be
    bit-identical to the bit-serial GF(2^128) reference — checked
    dependency-free (the cryptography-package cross-checks don't run
    in every container)."""
    from firedancer_tpu.ballet import aes as A

    rng = np.random.default_rng(13)
    for _ in range(2):
        h = rng.integers(0, 256, 16, np.uint8).tobytes()
        g = A.Ghash(h)
        hi = int.from_bytes(h, "big")
        for pos in (0, 5, 15):
            for b in (0, 1, 2, 0x80, 0xA5, 0xFF):
                assert g.table[pos][b] == A._gf128_mul(
                    hi, b << (8 * (15 - pos))
                )


def test_aes_gcm_nist_vectors_dependency_free():
    """NIST GCM test vectors (AES-128, 96-bit IV) pin the whole AEAD —
    key schedule, CTR stream, GHASH, tag — with no external package."""
    from firedancer_tpu.ballet import aes as A

    # McGrew-Viega test case 1: empty pt, zero key/iv
    g = A.AesGcm(bytes(16))
    assert g.encrypt(bytes(12), b"", b"").hex() == (
        "58e2fccefa7e3061367f1d57a4e7455a"
    )
    # test case 3: 4-block pt, no aad
    key = bytes.fromhex("feffe9928665731c6d6a8f9467308308")
    iv = bytes.fromhex("cafebabefacedbaddecaf888")
    pt = bytes.fromhex(
        "d9313225f88406e5a55909c5aff5269a"
        "86a7a9531534f7da2e4c303d8a318a72"
        "1c3c0c95956809532fcf0e2449a6b525"
        "b16aedf5aa0de657ba637b391aafd255"
    )
    ct_tag = A.AesGcm(key).encrypt(iv, pt, b"")
    assert ct_tag.hex() == (
        "42831ec2217774244b7221b784d0d49c"
        "e3aa212f2c02a4e035c17e2329aca12e"
        "21d514b25466931c7d8f6a5aac84aa05"
        "1ba30b396a0aac973d58e091473f5985"
        "4d5c2af327cd64a62cf35abd2ba6fab4"
    )
    # decrypt round-trip + tag rejection
    assert A.AesGcm(key).decrypt(iv, ct_tag, b"") == pt
    bad = ct_tag[:-1] + bytes([ct_tag[-1] ^ 1])
    assert A.AesGcm(key).decrypt(iv, bad, b"") is None


def test_retry_aead_cached_and_round_trips():
    """The Retry integrity AEAD (spec-constant key, RFC 9001 5.8) is
    built ONCE (the 75 ms-per-Retry defense-cost bug), and a server-
    minted Retry still authenticates at the client — while a tampered
    tag is ignored (no token adopted, no CID switch)."""
    from firedancer_tpu.waltz import quic as Q

    assert Q._retry_aead() is Q._retry_aead()  # cached singleton
    client = Q.QuicClient()
    conn = client.conn
    odcid = conn.dcid
    srv = Q.QuicServer(b"\x07" * 32, retry=True)
    retry = srv._retry_packet(conn.scid, odcid, ("127.0.0.1", 7))
    scid_off = 5 + 1 + len(conn.scid) + 1
    retry_scid = retry[scid_off : scid_off + 8]
    # tampered tag first: must be ignored entirely
    bad = retry[:-1] + bytes([retry[-1] ^ 1])
    conn._on_retry(bad, retry_scid)
    assert conn.token == b"" and conn.dcid == odcid
    # genuine retry: token adopted, server-chosen CID adopted
    conn._on_retry(retry, retry_scid)
    assert conn.token != b"" and conn.dcid == retry_scid
    # and the server accepts its own token back from the same addr
    assert srv._check_token(conn.token, ("127.0.0.1", 7)) is not None
    assert srv._check_token(conn.token, ("6.6.6.6", 7)) is None


# ---------------------------------------------------------------------------
# wire edge: pre-allocation admission in QuicServer


def _initial_pkt(i: int) -> bytes:
    from firedancer_tpu.waltz import quic as Q

    return (
        bytes([0xC0]) + (1).to_bytes(4, "big")
        + bytes([8]) + int(i).to_bytes(8, "little")
        + bytes([8]) + bytes(8)
        + b"\x00" + Q.vi_enc(40) + bytes(40)
    )


def test_quic_server_admission_gates_before_allocation():
    from firedancer_tpu.waltz import quic as Q

    adm = ConnAdmission(
        AdmissionConfig(handshake_rate=1, handshake_burst=2)
    )
    srv = Q.QuicServer(b"\x07" * 32, admission=adm)
    srv.now_tick = 0
    for i in range(8):
        srv.on_datagram(_initial_pkt(i), (f"127.0.5.{i}", 4000))
    # burst of 2 admitted (and allocated); the rest refused pre-alloc
    # with a stateless Retry as the backoff signal
    assert len(srv.conns) >= 2
    assert srv.admit_drops["drop_handshake_rate"] == 6
    assert srv.admit_drops["retry_sent"] == 6
    retries = [d for d, _ in srv.stateless_out if (d[0] & 0xF0) == 0xF0]
    assert len(retries) == 6
    # malformed garbage never raises and never allocates
    before = len(srv.conns)
    srv.on_datagram(b"\x40" + bytes(60), ("127.0.6.1", 1))
    srv.on_datagram(b"\xc0\xff", ("127.0.6.2", 1))
    assert len(srv.conns) == before


def test_quic_server_handshake_flood_cannot_evict_established():
    """At the connection cap, the LRU eviction prefers a victim that
    never completed its handshake — a flood must not push out peers."""
    from firedancer_tpu.waltz import quic as Q

    srv = Q.QuicServer(b"\x07" * 32, max_conns=4)
    for i in range(4):
        srv.on_datagram(_initial_pkt(i), (f"127.0.7.{i}", 4000))
    assert len(srv.by_addr) == 4
    # mark one victim-candidate established (oldest in LRU order)
    est_addr = ("127.0.7.0", 4000)
    srv.by_addr[est_addr].established = True
    srv.on_datagram(_initial_pkt(99), ("127.0.8.1", 4000))
    assert est_addr in srv.by_addr  # survived; a zombie was evicted


def test_refused_initial_never_evicts_established():
    """An Initial that will be REFUSED (per-source cap) must not cost
    an existing peer its slot: the at-cap eviction runs only after
    every admission gate has passed."""
    from firedancer_tpu.waltz import quic as Q

    adm = ConnAdmission(AdmissionConfig(max_conns_per_source=1))
    srv = Q.QuicServer(b"\x07" * 32, max_conns=3, admission=adm)
    for i in range(3):
        srv.on_datagram(_initial_pkt(i), (f"127.0.9.{i}", 4000))
    assert len(srv.by_addr) == 3
    for a in list(srv.by_addr):
        srv.by_addr[a].established = True
    # 127.0.9.0 already holds its 1 allowed conn: its new Initial (new
    # port, same source IP) is refused at the source cap — and the full
    # table of established peers must be untouched
    before = set(srv.by_addr)
    srv.on_datagram(_initial_pkt(77), ("127.0.9.0", 5000))
    assert srv.admit_drops.get("drop_source_cap", 0) >= 1
    assert set(srv.by_addr) == before


# ---------------------------------------------------------------------------
# config plumbing


def test_config_parses_admission_and_stakes():
    from firedancer_tpu.app import config as C

    cfg = C.parse(
        """
[tiles.quic]
max_conns = 128
handshake_rate = 50
txn_rate = 200
backlog_cap = 512
low_stake = 777

[stakes]
"0x0a0b" = 500
"1.2.3.4:5" = 70000
"""
    )
    assert cfg.quic_admission is not None
    assert cfg.quic_admission.max_conns == 128
    assert cfg.quic_admission.handshake_rate == 50
    assert cfg.quic_admission.backlog_cap == 512
    t = StakeTable.from_config(
        cfg.stakes, low_stake=cfg.quic_admission.low_stake
    )
    assert t.weight(b"\x0a\x0b") == 500
    assert t.cls_of(b"\x0a\x0b") == ADM.CLASS_LOW  # < 777
    assert t.cls_of(b"1.2.3.4:5") == ADM.CLASS_HI
    # no admission keys -> None (permissive pre-hardening behavior)
    assert C.parse("[tiles.quic]\nquic_port = 1\n").quic_admission is None


def test_admission_config_roundtrip():
    a = AdmissionConfig(max_conns=7, txn_rate=9, shed_hi=0.5)
    b = AdmissionConfig.from_dict(a.to_dict())
    assert a == b
    # unknown keys are ignored (forward-compatible config docs)
    c = AdmissionConfig.from_dict({"max_conns": 3, "not_a_knob": 1})
    assert c.max_conns == 3


# ---------------------------------------------------------------------------
# slow: verify-layer poison resistance + the adversary smoke

pytestmark_slow = pytest.mark.slow


@pytest.mark.slow
def test_malformed_and_smallorder_batch_does_not_poison_neighbors():
    """A batch laced with non-canonical sigs and small-order pubkeys is
    rejected AT VERIFY while every honest neighbor in the same batch
    still lands, and the rejects are metered (verify_fail_txns)."""
    import time

    from firedancer_tpu.ballet import txn as T
    from firedancer_tpu.disco import Topology
    from firedancer_tpu.ops.ed25519 import golden, hostpath
    from firedancer_tpu.tiles import wire
    from firedancer_tpu.tiles.dedup import DedupTile
    from firedancer_tpu.tiles.sink import SinkTile
    from firedancer_tpu.tiles.synth import SynthTile, make_txn_pool
    from firedancer_tpu.tiles.verify import VerifyTile

    n_good = 12
    rows, szs, good = make_txn_pool(n_good, seed=77)
    assert good.all()

    # poison txns: STRUCTURALLY valid (they parse, they reach the
    # sig-verify lanes) but cryptographically rotten
    def lace(payload: bytes) -> None:
        nonlocal rows, szs
        desc = T.parse(payload)
        assert desc is not None, "poison txns must parse"
        full = wire.append_trailer(payload, desc)
        row = np.zeros((1, wire.LINK_MTU), np.uint8)
        row[0, : len(full)] = np.frombuffer(full, np.uint8)
        rows = np.vstack([rows, row])
        szs = np.append(szs, np.uint16(len(full)))

    base = bytes(rows[0, : szs[0] - wire.TRAILER_SZ])

    # 1) non-canonical s: a copy of an honest txn with s >= L
    L = (1 << 252) + 27742317777372353535851937790883648493
    bad_s = bytearray(base)
    bad_s[1 + 32 : 1 + 64] = (L + 5).to_bytes(32, "little")
    lace(bytes(bad_s))

    # 2) small-order A: payer pubkey is a blocklisted small-order point
    small = golden.small_order_blocklist()[0]
    sk = b"\x11" * 32
    body = T.build(
        [bytes(64)], [small, b"\x22" * 32, b"\x33" * 32],
        b"\x44" * 32, [(2, [0, 1], b"\x00" * 8)],
        readonly_unsigned_cnt=1,
    )
    desc = T.parse(body)
    sig = hostpath.sign(sk, desc.message(body))  # sig by SOME key
    lace(body[:1] + sig + body[1 + 64 :])

    # 3) small-order R: honest txn, R replaced by the identity point
    bad_r = bytearray(base)
    bad_r[1 : 1 + 32] = golden.small_order_blocklist()[0]
    lace(bytes(bad_r))

    n_total = len(szs)
    synth = SynthTile(rows, szs, total=n_total)
    verify = VerifyTile(
        msg_width=256, max_lanes=32, pre_dedup=False, device="off",
        device_fn=hostpath.verify_batch_digest_host, async_depth=2,
    )
    topo = Topology()
    topo.link("synth_verify", depth=64, mtu=wire.LINK_MTU)
    topo.link("verify_dedup", depth=64, mtu=wire.LINK_MTU)
    topo.link("dedup_sink", depth=64, mtu=wire.LINK_MTU)
    topo.tile(synth, outs=["synth_verify"])
    topo.tile(verify, ins=[("synth_verify", True)], outs=["verify_dedup"])
    topo.tile(DedupTile(depth=1 << 10), ins=[("verify_dedup", True)],
              outs=["dedup_sink"])
    sink = SinkTile(record=True)
    topo.tile(sink, ins=[("dedup_sink", True)])
    topo.build()
    topo.start(batch_max=64)
    try:
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            topo.poll_failure()
            if topo.metrics("sink").counter("sunk_frags") >= n_good:
                break
            time.sleep(0.02)
        topo.halt()
        mv = topo.metrics("verify")
        assert mv.counter("verify_fail_txns") == 3  # all poison metered
        tags = set(sink.all_sigs().tolist())
        want = set(synth.tags[:n_good].tolist())
        assert tags == want  # every honest neighbor landed, no poison
    finally:
        topo.close()


@pytest.mark.slow
def test_adversary_smoke_thread_runtime():
    """Bounded seeded adversarial run — the full invariant set
    (zero crashes, exactly-once staked delivery, exact drop ledger,
    escalation incidents classified, staked SLO holds)."""
    from scripts.adversary import run_adversary

    rep = run_adversary(seed=7, staked=32, duration_s=8.0)
    assert rep["ok"], json.dumps(rep.get("checks"), indent=1)
