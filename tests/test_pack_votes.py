"""Pack vote lane: votes-first scheduling, vote CU budgets, and a
randomized property test of the dense engine against a straightforward
oracle (VERDICT round-1 item 5)."""

import numpy as np
import pytest

from firedancer_tpu.ballet import pack as P
from firedancer_tpu.ballet import txn as T


def _mk_txn(rng, *, vote: bool, writable_key: bytes | None = None,
            signer: bytes | None = None,
            program: bytes | None = None) -> bytes:
    """A minimal txn; vote txns have one instr on the Vote program.
    Non-vote default is an unknown (BPF-costed) program; pass program=
    bytes(32) for a cheap builtin-costed txn."""
    signer = signer or rng.integers(0, 256, 32, np.uint8).tobytes()
    acct = writable_key or rng.integers(0, 256, 32, np.uint8).tobytes()
    if program is None:
        program = P.VOTE_PROGRAM_ID if vote else bytes(31) + b"\x01"
    blockhash = rng.integers(0, 256, 32, np.uint8).tobytes()
    data = rng.integers(0, 256, 16, np.uint8).tobytes()
    body = T.build(
        [rng.integers(0, 256, 64, np.uint8).tobytes()],
        [signer, acct, program],
        blockhash,
        [(2, [0, 1], data)],
        readonly_unsigned_cnt=1,
    )
    return body


def test_is_simple_vote():
    rng = np.random.default_rng(0)
    v = _mk_txn(rng, vote=True)
    n = _mk_txn(rng, vote=False)
    assert P.is_simple_vote(v, T.parse(v))
    assert not P.is_simple_vote(n, T.parse(n))


def test_votes_scheduled_first_and_budgeted():
    rng = np.random.default_rng(1)
    pk = P.Pack(256)
    for _ in range(20):
        assert pk.insert(_mk_txn(rng, vote=True)) == "ok"
    for _ in range(20):
        # builtin-costed non-votes (system program): cheap enough to
        # share a microblock whose budget is sized in vote costs
        assert pk.insert(_mk_txn(rng, vote=False, program=bytes(32))) == "ok"
    vote_cost = int(pk.cost[pk.is_vote & (pk.state == 1)][0])

    # a budget that fits exactly 3 votes at 25% of the CU limit
    cu_limit = vote_cost * 3 * 4
    mb = pk.schedule_microblock(0, cu_limit=cu_limit, txn_limit=31)
    assert mb is not None
    picked_votes = int(pk.is_vote[mb.txn_idx].sum())
    assert picked_votes == 3  # vote_fraction * cu_limit / vote_cost
    assert picked_votes < len(mb.txn_idx)  # non-votes filled the rest
    # votes come first in the microblock
    assert pk.is_vote[mb.txn_idx[:picked_votes]].all()
    assert pk.cumulative_vote_cost == picked_votes * vote_cost


def test_vote_block_cap_enforced():
    rng = np.random.default_rng(2)
    pk = P.Pack(64)
    for _ in range(8):
        assert pk.insert(_mk_txn(rng, vote=True)) == "ok"
    vote_cost = int(pk.cost[pk.state == 1][0])
    # shrink the per-block vote cap to 2 votes' worth
    pk.vote_cost_limit = 2 * vote_cost
    mb = pk.schedule_microblock(0, cu_limit=10_000_000, txn_limit=31,
                                vote_fraction=1.0)
    assert mb is not None and len(mb.txn_idx) == 2
    pk.microblock_complete(0, mb.handle)
    # cap reached: no more votes this block
    assert pk.schedule_microblock(
        0, cu_limit=10_000_000, txn_limit=31, vote_fraction=1.0
    ) is None
    # next block resets the vote budget
    pk.end_block()
    mb2 = pk.schedule_microblock(0, cu_limit=10_000_000, txn_limit=31,
                                 vote_fraction=1.0)
    assert mb2 is not None and len(mb2.txn_idx) == 2


def _oracle_schedule(txns, in_use, cu_limit, vote_budget, txn_limit,
                     vote_fraction):
    """Straightforward model: priority order, votes first (with CU and
    txn-slot vote budgets), conflict via exact account sets, greedy skip."""
    chosen = []
    used = set(in_use)
    cu = 0
    vcu = 0
    any_nonvote = any(not t["vote"] and t["pending"] for t in txns)
    vote_slots = (
        max(1, int(txn_limit * vote_fraction)) if any_nonvote else txn_limit
    )
    n_votes = 0
    for lane in (True, False):
        cands = [t for t in txns if t["vote"] == lane and t["pending"]]
        cands.sort(key=lambda t: (-t["prio"], t["order"]))
        for t in cands:
            if len(chosen) >= txn_limit:
                break
            if lane and n_votes >= vote_slots:
                break
            if cu + t["cost"] > cu_limit:
                continue
            if lane and vcu + t["cost"] > vote_budget:
                continue
            if used & t["accts"]:
                continue
            chosen.append(t["id"])
            used |= t["accts"]
            cu += t["cost"]
            if lane:
                vcu += t["cost"]
                n_votes += 1
    return chosen


def test_randomized_vs_oracle():
    """With collision-free account hashing (few accounts, big bitset), the
    dense engine must match the oracle exactly."""
    rng = np.random.default_rng(3)
    nbits = 4096

    seen = {
        P._hash_acct(P.VOTE_PROGRAM_ID) % nbits,
        P._hash_acct(bytes(31) + b"\x01") % nbits,
    }

    def fresh_keys(n):
        """Distinct keys whose hashed bits are collision-free against
        everything issued so far, so bitset conflicts == exact conflicts."""
        out = []
        while len(out) < n:
            k = rng.integers(0, 256, 32, np.uint8).tobytes()
            h = P._hash_acct(k) % nbits
            if h not in seen:
                seen.add(h)
                out.append(k)
        return out

    keys = fresh_keys(12)

    for trial in range(8):
        pk = P.Pack(128, nbits=nbits)
        model = []
        n = int(rng.integers(6, 24))
        signers = fresh_keys(n)
        for i in range(n):
            vote = bool(rng.integers(0, 2))
            wk = keys[rng.integers(0, len(keys))]
            body = _mk_txn(rng, vote=vote, writable_key=wk, signer=signers[i])
            assert pk.insert(body) == "ok"
            desc = T.parse(body)
            accts = {
                bytes(desc.acct_addr(body, j)) for j in desc.writable_idxs()
            }
            slot = i  # inserts fill slots in order in an empty pool
            model.append(
                {
                    "id": slot,
                    "vote": vote,
                    "cost": int(pk.cost[slot]),
                    "prio": float(pk.rewards[slot]) / max(int(pk.cost[slot]), 1),
                    "accts": accts,
                    "order": i,
                    "pending": True,
                }
            )
        cu_limit = int(rng.integers(1, 8)) * int(pk.cost[0])
        vf = float(rng.choice([0.0, 0.25, 1.0]))
        mb = pk.schedule_microblock(
            0, cu_limit=cu_limit, txn_limit=8, vote_fraction=vf
        )
        want = _oracle_schedule(
            model, set(), cu_limit, int(cu_limit * vf), 8, vf
        )
        got = [] if mb is None else [int(s) for s in mb.txn_idx]
        assert got == want, f"trial {trial}: {got} != {want}"
