"""fdt_bank: the native shared-memory batch executor (tier-1, ISSUE 9).

Contracts pinned here:

  1. differential fuzz — randomized fast-transfer batches (duplicate
     keys, dst==payer, absent dst, underfunded and below-fee payers,
     self-transfers, zero-lamport transfers with the
     system_transfer_zero_check feature on AND off, NONTRIVIAL-account
     fallbacks mixed in) must produce fees/stats/post-state IDENTICAL
     to the execute_txn golden applied in the same order;
  2. crash safety — a bank process SIGKILLed mid-slot leaves the shm
     table equal to the golden prefix after recover() (undo-journal
     rollback + dirty drain), and the resumed execution applies each
     txn exactly once (zero lost / zero duplicated lamports);
  3. robustness — a malformed microblock is a metered drop that still
     frees the bank at pack; a full table falls back to the general
     executor without diverging.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from firedancer_tpu.ballet import pack as P
from firedancer_tpu.ballet import txn as T
from firedancer_tpu.flamenco.accounts import (
    Account, AccountMgr, SYSTEM_PROGRAM_ID,
)
from firedancer_tpu.flamenco.features import DISABLED
from firedancer_tpu.flamenco.runtime import BankTable, Executor
from firedancer_tpu.disco.metrics import MetricsSchema as _MetricsSchema
from firedancer_tpu.disco.mux import Tile as _MuxTile
from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.tango import rings as R

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _key(rng) -> bytes:
    return bytes(rng.integers(0, 256, 32, np.uint8))


def _xfer(payer: bytes, dest: bytes, amount: int) -> bytes:
    data = (2).to_bytes(4, "little") + amount.to_bytes(8, "little")
    return T.build(
        [bytes(64)], [payer, dest, SYSTEM_PROGRAM_ID], bytes(32),
        [(2, [0, 1], data)], readonly_unsigned_cnt=1,
    )


def _self_xfer(payer: bytes, amount: int) -> bytes:
    data = (2).to_bytes(4, "little") + amount.to_bytes(8, "little")
    return T.build(
        [bytes(64)], [payer, SYSTEM_PROGRAM_ID], bytes(32),
        [(1, [0, 0], data)], readonly_unsigned_cnt=1,
    )


def _xfer2(payer: bytes, src: bytes, dest: bytes, amount: int) -> bytes:
    """Two-signer transfer where the SOURCE is the second signer, not
    the fee payer — the only shape that reaches the absent/underfunded
    source branches (a payer-source always exists once the fee
    cleared)."""
    data = (2).to_bytes(4, "little") + amount.to_bytes(8, "little")
    return T.build(
        [bytes(64), bytes(64)], [payer, src, dest, SYSTEM_PROGRAM_ID],
        bytes(32), [(3, [1, 2], data)], readonly_unsigned_cnt=1,
    )


def _pack_rows(txns):
    width = max(len(t) for t in txns)
    rows = np.zeros((len(txns), width), np.uint8)
    szs = np.zeros(len(txns), np.uint32)
    for i, t in enumerate(txns):
        rows[i, : len(t)] = np.frombuffer(t, np.uint8)
        szs[i] = len(t)
    return rows, szs


def _fund(funding):
    funk = Funk()
    mgr = AccountMgr(funk)
    for k, acct in funding.items():
        mgr.store(k, acct)
    ex = Executor(funk)
    ex.begin_slot(0)
    return funk, ex


def _snap(funk):
    mgr = AccountMgr(funk)
    return {
        k: (a.lamports, a.owner, a.data)
        for k, a in ((k, mgr.load(k)) for k in funk.root)
        if a is not None
    }


def _run_native(txns, funding, *, slots=1 << 10, zero_check=True, tag=1):
    funk, ex = _fund(funding)
    if not zero_check:
        ex.features.slots["system_transfer_zero_check"] = DISABLED
    rows, szs = _pack_rows(txns)
    scan = P.txn_scan(rows, szs)
    assert scan.ok.all() and scan.fast.all(), "fixture must be fast-class"
    tab = BankTable(np.zeros(BankTable.footprint(slots), np.uint8), slots)
    stats = ex.execute_fast_transfers_native(
        tab, rows, szs, np.arange(len(txns), dtype=np.int64), scan, tag=tag
    )
    tab.commit(funk)
    return funk, stats, tab


def _run_golden(txns, funding, *, zero_check=True):
    funk, ex = _fund(funding)
    if not zero_check:
        ex.features.slots["system_transfer_zero_check"] = DISABLED
    fees = executed = failed = 0
    for t in txns:
        r = ex.execute_txn(t)
        fees += r.fee
        executed += 1
        failed += not r.ok
    return funk, (fees, executed, failed)


# ---------------------------------------------------------------------------
# 1. differential fuzz vs the execute_txn golden


def _fuzz_batch(rng, n_txns=48):
    """A batch exercising every fast-path edge at once, plus NONTRIVIAL
    fallbacks.  Returns (txns, funding)."""
    owner = _key(rng)
    payers = [_key(rng) for _ in range(6)]
    dests = [_key(rng) for _ in range(4)]
    prog_owned = _key(rng)
    data_acct = _key(rng)
    poor = _key(rng)
    broke = _key(rng)
    funding = {
        **{p: Account(int(rng.integers(20_000, 2_000_000)))
           for p in payers},
        poor: Account(5_000 + int(rng.integers(0, 400))),
        broke: Account(int(rng.integers(0, 5_000))),
        prog_owned: Account(777, owner, False, 0, b"state"),
        data_acct: Account(999, SYSTEM_PROGRAM_ID, False, 0, b"d"),
    }
    txns = []
    for _ in range(n_txns):
        kind = int(rng.integers(0, 13))
        p = payers[int(rng.integers(0, len(payers)))]
        amt = int(rng.integers(1, 9_999))
        if amt % 5_000 == 0:
            amt += 1  # torn-txn detectability (see crash test)
        if kind == 10:
            # source (2nd signer) ABSENT: fee stands, transfer fails —
            # except a 0-lamport transfer pre-zero_check (silent no-op)
            z_amt = amt if rng.integers(0, 2) else 0
            txns.append(_xfer2(p, _key(rng), dests[0], z_amt))
        elif kind == 11:
            # source underfunded relative to the amount (fee from payer)
            txns.append(_xfer2(p, poor, dests[1], 900_000))
        elif kind == 12:
            # source == dest via distinct offsets (self-transfer no-op)
            q = payers[int(rng.integers(0, len(payers)))]
            txns.append(_xfer2(p, q, q, amt))
        elif kind == 0:
            txns.append(_xfer(poor, dests[0], 900_000))     # underfunded
        elif kind == 1:
            txns.append(_xfer(broke, dests[0], 1))          # below fee
        elif kind == 2:
            txns.append(_self_xfer(p, amt))                 # self no-op
        elif kind == 3:
            txns.append(_xfer(p, p, amt))                   # dst == payer
        elif kind == 4:
            txns.append(_xfer(p, prog_owned, amt))          # NONTRIV dst
        elif kind == 5:
            txns.append(_xfer(p, data_acct, amt))           # NONTRIV dst 2
        elif kind == 6:
            txns.append(_xfer(p, _key(rng), 0))             # 0 to absent
        elif kind == 7:
            # payer another payer (duplicate-key aliasing in-batch)
            q = payers[int(rng.integers(0, len(payers)))]
            txns.append(_xfer(p, q, amt))
        else:
            txns.append(
                _xfer(p, dests[int(rng.integers(0, len(dests)))], amt)
            )
    return txns, funding


@pytest.mark.parametrize("seed", [101, 202, 303, 404])
@pytest.mark.parametrize("zero_check", [True, False])
def test_fuzz_native_matches_golden(seed, zero_check):
    rng = np.random.default_rng(seed)
    for _ in range(3):
        txns, funding = _fuzz_batch(rng)
        nf, ns, _tab = _run_native(
            txns, funding, zero_check=zero_check, tag=seed
        )
        gf, gs = _run_golden(txns, funding, zero_check=zero_check)
        assert ns == gs, f"stats diverged (seed {seed})"
        assert _snap(nf) == _snap(gf), f"post-state diverged (seed {seed})"


def test_sequential_dependency_and_warm_table_reuse():
    """txn k+1 spends what txn k landed; a second batch on the warm
    table (zero misses -> one native call) stays golden-equal."""
    rng = np.random.default_rng(7)
    a, b, c = _key(rng), _key(rng), _key(rng)
    funding = {a: Account(1_000_000), b: Account(10_000)}
    batch1 = [_xfer(a, b, 500_000), _xfer(b, c, 490_000)]
    batch2 = [_xfer(c, a, 123_457), _xfer(b, a, 1)]

    funk_n, ex_n = _fund(funding)
    tab = BankTable(np.zeros(BankTable.footprint(256), np.uint8), 256)
    funk_g, ex_g = _fund(funding)
    for tag, batch in ((1, batch1), (2, batch2)):
        rows, szs = _pack_rows(batch)
        scan = P.txn_scan(rows, szs)
        ex_n.execute_fast_transfers_native(
            tab, rows, szs, np.arange(len(batch), dtype=np.int64), scan,
            tag=tag,
        )
        tab.commit(funk_n)
        for t in batch:
            ex_g.execute_txn(t)
    assert _snap(funk_n) == _snap(funk_g)


def test_table_full_falls_back_without_divergence():
    """A table too small for the working set must fail CLOSED: txns the
    table cannot host run through the general executor, and the result
    still equals golden."""
    rng = np.random.default_rng(11)
    txns, funding = _fuzz_batch(rng, n_txns=32)
    nf, ns, _ = _run_native(txns, funding, slots=4, tag=3)
    gf, gs = _run_golden(txns, funding)
    assert ns == gs
    assert _snap(nf) == _snap(gf)


def test_commit_keeps_lam_cache_discipline():
    """commit() write-backs must leave funk.lam_cache holding exactly
    the decoded lamports of the live root record (the coherence rule
    execute_fast_transfers established)."""
    rng = np.random.default_rng(13)
    p, d = _key(rng), _key(rng)
    funding = {p: Account(1_000_000)}
    funk, stats, tab = _run_native([_xfer(p, d, 100)], funding, tag=9)
    assert stats == (5000, 1, 0)
    mgr = AccountMgr(funk)
    assert funk.lam_cache[p] == mgr.load(p).lamports == 1_000_000 - 5_100
    assert funk.lam_cache[d] == mgr.load(d).lamports == 100
    # table and funk agree (the table is the authoritative copy)
    assert tab.get(p) == (BankTable.ST_TRIVIAL, 1_000_000 - 5_100)


# ---------------------------------------------------------------------------
# 2. crash safety: journal rollback + SIGKILL mid-slot


def test_journal_rollback_restores_slots():
    """A journal left in phase=APPLYING (killed between the undo record
    and the done-count advance) must roll its slots back exactly and
    re-mark them dirty for the funk drain."""
    slots = 64
    tab = BankTable(np.zeros(BankTable.footprint(slots), np.uint8), slots)
    key_a, key_b = bytes(range(32)), bytes(range(32, 64))
    assert tab.put(key_a, BankTable.ST_TRIVIAL, 1000)
    assert tab.put(key_b, BankTable.ST_ABSENT, 0)
    # find the slot indices via a drain-free probe: hash order is
    # implementation detail, so locate by get + brute scan of the region
    mem = tab.mem
    slot_words = mem[64:].view(np.uint64).reshape(slots, 8)
    idx = {}
    for i in range(slots):
        kb = slot_words[i, :4].tobytes()
        if kb == key_a:
            idx[key_a] = i
        elif kb == key_b:
            idx[key_b] = i
    # simulate a crash mid-apply: slots already mutated, journal armed
    # (the done-count was advanced but the phase never cleared, so the
    # rollback must ALSO rewind done to the pre-txn count)
    tab.put(key_a, BankTable.ST_TRIVIAL, 42, dirty=True)
    tab.put(key_b, BankTable.ST_TRIVIAL, 43, dirty=True)
    jw = tab._jw
    jw[0] = 77   # tag
    jw[1] = 4    # txns done (already advanced for the in-flight txn)
    jw[2] = 1    # phase: APPLYING
    jw[3] = 2    # undo entries
    jw[4] = 3    # done-count BEFORE the in-flight txn
    jw[5:8] = (idx[key_a], BankTable.ST_TRIVIAL, 1000)
    jw[8:11] = (idx[key_b], BankTable.ST_ABSENT, 0)
    funk = Funk()
    tag, done, rolled = tab.recover(funk)
    assert rolled and (tag, done) == (77, 3), "done must rewind to pre-txn"
    assert tab.get(key_a) == (BankTable.ST_TRIVIAL, 1000)
    assert tab.get(key_b)[0] == BankTable.ST_ABSENT
    # the rollback re-dirtied both: the drain restored funk's view
    assert funk.rec_read(b"\x00" * 32, key_a) is not None
    assert funk.rec_read(b"\x00" * 32, key_b) is None
    assert int(tab._jw[2]) == 0


def test_mid_microblock_resume_applies_exactly_once():
    """A bank that died with a microblock half done must resume at the
    journal's txn count: re-running the WHOLE batch under the same tag
    applies only the unapplied suffix (the dead incarnation's prefix is
    skipped via the shm journal, not re-executed)."""
    rng = np.random.default_rng(17)
    pool = [_key(rng) for _ in range(8)]
    funding = {k: Account(1_000_000) for k in pool}
    txns = [
        _xfer(pool[i % 8], pool[(i + 3) % 8], 1_001 + 7 * i)
        for i in range(16)
    ]
    funk, ex = _fund(funding)
    tab = BankTable(np.zeros(BankTable.footprint(256), np.uint8), 256)
    rows, szs = _pack_rows(txns)
    scan = P.txn_scan(rows, szs)
    idx = np.arange(16, dtype=np.int64)
    # "crash" after 7 txns: run the prefix only, then replay the whole
    # microblock under the same tag as a restarted bank would
    ex.execute_fast_transfers_native(tab, rows, szs, idx[:7], scan, tag=55)
    assert int(tab._jw[1]) == 7
    start = tab.begin(55)
    assert start == 7
    ex.execute_fast_transfers_native(
        tab, rows, szs, idx, scan, tag=55, start=start
    )
    tab.commit(funk)
    gfunk, gex = _fund(funding)
    for t in txns:
        gex.execute_txn(t)
    assert _snap(funk) == _snap(gfunk)


def test_replayed_completed_microblock_never_reexecutes():
    """The supervisor's restart replay redelivers MANY microblocks (the
    consumer fseq only advances at housekeeping cadence), not just the
    half-done one — every fully-completed microblock below the journal's
    completed-seq mark must re-publish but never re-execute, or the
    surviving shm table double-applies its transfers."""
    from firedancer_tpu.disco.metrics import Metrics
    from firedancer_tpu.disco.mux import MuxCtx
    from firedancer_tpu.tiles.bank import BankTile

    rng = np.random.default_rng(41)
    a, b = _key(rng), _key(rng)
    funk, _ = _fund({a: Account(1_000_000), b: Account(1_000_000)})
    bank = BankTile(0, funk=funk, table_slots=256)
    ctx = MuxCtx(
        "bank0",
        R.CNC(np.zeros(R.CNC.footprint(), np.uint8)),
        [], [],
        Metrics(
            np.zeros(Metrics.footprint(bank.schema), np.uint8), bank.schema
        ),
    )
    bank.on_boot(ctx)
    txns = [_xfer(a, b, 1_111), _xfer(b, a, 2_223)]
    rows, szs = _pack_rows(txns)

    def _lam(k):
        return AccountMgr(funk).load(k).lamports

    fees = bank._execute(ctx, rows, szs, tag=100)
    bank._commit(ctx)
    assert fees == 10_000
    snap = (_lam(a), _lam(b))
    # redelivery of the SAME and of an EARLIER frag seq: skipped whole
    assert bank._execute(ctx, rows, szs, tag=100) is None
    assert bank._execute(ctx, rows, szs, tag=99) is None
    bank._commit(ctx)
    assert (_lam(a), _lam(b)) == snap, "replayed microblock re-executed"
    # a genuinely NEW microblock still executes
    assert bank._execute(ctx, rows, szs, tag=101) == 10_000


# -- SIGKILL harness --------------------------------------------------------

RESTART_SLOTS = 1 << 10
RESTART_BATCH_N = 16
RESTART_BATCHES = 64


def _restart_corpus(seed: int):
    """Deterministic corpus shared by parent, child, and golden: chained
    fast transfers over a small account pool.  Amounts are never
    multiples of the 5000 fee so a torn (half-applied) txn cannot hide
    inside a fee-shaped delta."""
    rng = np.random.default_rng(seed)
    pool = [_key(rng) for _ in range(24)]
    funding = {
        k: Account(int(rng.integers(1_000_000, 5_000_000))) for k in pool
    }
    txns = []
    for _ in range(RESTART_BATCHES * RESTART_BATCH_N):
        a = pool[int(rng.integers(0, len(pool)))]
        b = pool[int(rng.integers(0, len(pool)))]
        amt = int(rng.integers(1, 50_000))
        if amt % 5_000 == 0:
            amt += 1
        txns.append(_xfer(a, b, amt))
    return pool, funding, txns


def _exec_batches(tab, ex, txns, first_batch, last_batch, prog=None,
                  sleep_s=0.0):
    rows, szs = _pack_rows(txns)
    scan = P.txn_scan(rows, szs)
    for b in range(first_batch, last_batch):
        lo = b * RESTART_BATCH_N
        idx = np.arange(lo, lo + RESTART_BATCH_N, dtype=np.int64)
        tag = 1000 + b
        start = tab.begin(tag)
        ex.execute_fast_transfers_native(
            tab, rows, szs, idx, scan, tag=tag, start=start
        )
        if prog is not None:
            prog[0] = b + 1
        if sleep_s:
            time.sleep(sleep_s)


def _restart_child(wksp_name: str, seed: int) -> None:
    """The 'bank process': executes the corpus batch by batch against
    the shm table until killed."""
    ws, _extra = R.Workspace.attach(wksp_name)
    tab = BankTable(
        ws.view("shared_banktab"), RESTART_SLOTS, journal=ws.view("jnl")
    )
    prog = ws.view("prog")[:16].view(np.uint64)
    _pool, funding, txns = _restart_corpus(seed)
    funk, ex = _fund(funding)
    prog[1] = os.getpid()  # ready signal for the parent's kill timer
    _exec_batches(tab, ex, txns, int(prog[0]), RESTART_BATCHES, prog=prog,
                  sleep_s=0.002)


_CHILD_SCRIPT = """
import sys
sys.path.insert(0, {repo!r})
sys.path.insert(0, {tests!r})
import test_bank_native as M
M._restart_child({name!r}, {seed})
"""


@pytest.mark.parametrize("seed", [5, 6])
def test_sigkill_restart_zero_lost_zero_duplicated(seed, tmp_path):
    """SIGKILL a bank process mid-slot; after shm-table rejoin +
    recover(), the table must equal the golden prefix EXACTLY (the
    journal names how many txns landed), and resuming applies the rest
    exactly once — final state equals the full golden run."""
    name = f"banktest_{os.getpid()}_{seed}"
    ws = R.Workspace(BankTable.footprint(RESTART_SLOTS) + 8192, name=name)
    try:
        ws.alloc("shared_banktab", BankTable.footprint(RESTART_SLOTS))
        ws.alloc("jnl", BankTable.JOURNAL_BYTES)
        ws.alloc("prog", 128)
        ws.publish_directory()
        prog = ws.view("prog")[:16].view(np.uint64)

        p = subprocess.Popen(
            [sys.executable, "-c", _CHILD_SCRIPT.format(
                repo=REPO, tests=os.path.join(REPO, "tests"),
                name=name, seed=seed,
            )],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        try:
            deadline = time.monotonic() + 60.0
            while not int(prog[1]):
                assert p.poll() is None, "child died before executing"
                assert time.monotonic() < deadline, "child never started"
                time.sleep(0.005)
            # let it get partway into the slot, then SIGKILL mid-flight
            time.sleep(0.02 + 0.03 * (seed % 3))
            os.kill(p.pid, signal.SIGKILL)
        finally:
            p.wait(timeout=30)

        # ---- restart: rejoin the shm table, roll back, resume --------
        pool, funding, txns = _restart_corpus(seed)
        funk, ex = _fund(funding)
        tab = BankTable(
            ws.view("shared_banktab"), RESTART_SLOTS,
            journal=ws.view("jnl"),
        )
        assert tab.rejoined
        tag, done, _rolled = tab.recover(funk, ex.xid)
        batches_done = int(prog[0])
        if tag >= 1000:
            applied = max(
                batches_done * RESTART_BATCH_N,
                (tag - 1000) * RESTART_BATCH_N + done,
            )
        else:
            applied = batches_done * RESTART_BATCH_N
        assert 0 <= applied <= len(txns)

        # golden prefix: exactly `applied` txns landed, none torn
        gfunk, gex = _fund(funding)
        for t in txns[:applied]:
            gex.execute_txn(t)
        gmgr = AccountMgr(gfunk)
        for k in pool:
            want = gmgr.load(k)
            st, lam = tab.get(k)
            if st == BankTable.ST_EMPTY:
                # never cached: the account was never touched natively
                assert want.lamports == funding[k].lamports, (
                    "untouched account diverged"
                )
            else:
                assert st == BankTable.ST_TRIVIAL
                assert lam == want.lamports, (
                    f"lamports diverged after kill (applied={applied})"
                )

        # resume from the journal: every remaining txn exactly once
        current = (tag - 1000) if tag >= 1000 else batches_done
        _exec_batches(tab, ex, txns, max(current, 0), RESTART_BATCHES)
        tab.commit(funk, ex.xid)
        for t in txns[applied:]:
            gex.execute_txn(t)
        for k in pool:
            st, lam = tab.get(k)
            assert st == BankTable.ST_TRIVIAL
            assert lam == gmgr.load(k).lamports, "resume lost/duplicated"
    finally:
        ws.unlink()


# ---------------------------------------------------------------------------
# 3. process-runtime sharding: every bank child maps ONE shared table


class _ProbeTile(_MuxTile):
    """Minimal proc-safe tile asserting ctx.shared crosses the process
    boundary: each shard writes its pid into the SAME region.  Module
    level so multiprocessing spawn can unpickle it in the child."""

    schema = _MetricsSchema()

    def __init__(self, i: int):
        self.i = i
        self.name = f"probe{i}"

    def shared_wksp_footprints(self):
        return {"probetab": 4096}

    def on_boot(self, ctx):
        w = ctx.shared("probetab", 4096)[:64].view(np.uint64)
        w[self.i] = os.getpid()


def test_process_shards_map_one_shared_region():
    """Two tiles under the process runtime must resolve ctx.shared to
    the parent's single workspace allocation — the mechanism that lets
    N bank processes execute against one account table."""
    from firedancer_tpu.disco import Topology

    topo = Topology(name=f"shardprobe_{os.getpid()}", runtime="process")
    topo.tile(_ProbeTile(0))
    topo.tile(_ProbeTile(1))
    topo.build()
    topo.start(boot_timeout_s=300.0)
    try:
        w = topo.wksp.view("shared_probetab")[:64].view(np.uint64)
        deadline = time.monotonic() + 30.0
        while not (int(w[0]) and int(w[1])):
            topo.poll_failure()
            assert time.monotonic() < deadline
            time.sleep(0.01)
        pids = {int(w[0]), int(w[1])}
        assert len(pids) == 2 and os.getpid() not in pids, (
            "shards must be distinct child processes writing one region"
        )
    finally:
        topo.halt()
        topo.close()


# ---------------------------------------------------------------------------
# 4. the bank tile: malformed microblocks are a metered drop


def _mb_encode(handle: int, bank: int, txns) -> bytes:
    out = (
        handle.to_bytes(4, "little")
        + bank.to_bytes(2, "little")
        + len(txns).to_bytes(2, "little")
    )
    for t in txns:
        out += len(t).to_bytes(2, "little") + t
    return out


def test_malformed_microblock_is_metered_drop():
    """A truncated microblock must not kill the bank tile NOR leak its
    pack handle: the tile meters `malformed_microblocks`, publishes the
    completion (freeing the bank at pack), forwards nothing to poh, and
    keeps executing subsequent valid microblocks."""
    from firedancer_tpu.disco import Topology
    from firedancer_tpu.disco.mux import OutLink
    from firedancer_tpu.tiles.bank import BankTile

    rng = np.random.default_rng(21)
    payer, dest = _key(rng), _key(rng)
    funk = Funk()
    AccountMgr(funk).store(payer, Account(1_000_000))

    topo = Topology()
    topo.link("pack_bank0", depth=64, mtu=65_535)
    topo.link("bank0_pack", depth=64)
    topo.link("bank0_poh", depth=64, mtu=65_535)
    bank = BankTile(0, funk=funk)
    topo.tile(
        bank, ins=[("pack_bank0", True)],
        outs=["bank0_pack", "bank0_poh"],
    )
    topo.build()
    feeder = OutLink(
        "pack_bank0", topo._mcaches["pack_bank0"],
        topo._dcaches["pack_bank0"],
        [topo._fseqs[("pack_bank0", "bank0")]],
    )
    topo.start()
    try:
        good = _mb_encode(1, 0, [_xfer(payer, dest, 500)])
        # claims 3 txns, carries half of one: fdt_mb_decode fails
        bad = bytearray(_mb_encode(2, 0, [_xfer(payer, dest, 7)]))
        bad[6:8] = (3).to_bytes(2, "little")
        for payload in (bytes(bad), good):
            row = np.frombuffer(payload, np.uint8)[None, :]
            feeder.publish(
                np.array([0], np.uint64), row,
                np.array([len(payload)], np.uint16),
            )
        m = topo.metrics("bank0")
        deadline = time.monotonic() + 30.0
        while m.counter("executed_microblocks") < 1:
            topo.poll_failure()  # the tile must NOT die on the bad frag
            assert time.monotonic() < deadline
            time.sleep(0.01)
        assert m.counter("malformed_microblocks") == 1
        assert m.counter("executed_microblocks") == 1  # only the good one
        # BOTH frags completed back to pack (handle freed), but only the
        # good one was forwarded to poh
        assert topo._mcaches["bank0_pack"].seq_query() == 2
        assert topo._mcaches["bank0_poh"].seq_query() == 1
        # and the good one really executed through the native table;
        # the funk write-back lands on the housekeeping commit cadence
        assert m.counter("native_txns") == 1
        mgr = AccountMgr(funk)
        while mgr.load(dest) is None:
            topo.poll_failure()
            assert time.monotonic() < deadline, "commit never drained"
            time.sleep(0.01)
        assert mgr.load(dest).lamports == 500
    finally:
        topo.halt()
        topo.close()


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-x", "-q"]))
