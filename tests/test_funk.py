"""funk fork-tree semantics: shadowing reads, publish/cancel, competing
forks, frozen rule, tombstones, checkpoint/restore."""

import numpy as np
import pytest

from firedancer_tpu.funk import ROOT_XID, Funk


def _xid(i):
    return bytes([i]) + bytes(31)


def test_read_through_ancestry():
    f = Funk()
    f.rec_write(ROOT_XID, b"a", b"root-a")
    f.txn_prepare(ROOT_XID, _xid(1))
    f.txn_prepare(_xid(1), _xid(2))
    f.rec_write(_xid(2), b"b", b"x2-b")
    assert f.rec_read(_xid(2), b"a") == b"root-a"  # falls through
    assert f.rec_read(_xid(2), b"b") == b"x2-b"
    assert f.rec_read(_xid(1), b"b") is None  # not visible to ancestor
    assert f.rec_read(ROOT_XID, b"b") is None


def test_shadowing_and_tombstone():
    f = Funk()
    f.rec_write(ROOT_XID, b"k", b"v0")
    f.txn_prepare(ROOT_XID, _xid(1))
    f.rec_write(_xid(1), b"k", b"v1")
    assert f.rec_read(_xid(1), b"k") == b"v1"
    assert f.rec_read(ROOT_XID, b"k") == b"v0"
    f.rec_remove(_xid(1), b"k")
    assert f.rec_read(_xid(1), b"k") is None  # tombstone shadows root
    assert f.rec_read(ROOT_XID, b"k") == b"v0"
    f.txn_publish(_xid(1))
    assert f.rec_read(ROOT_XID, b"k") is None  # removal published


def test_publish_chain_cancels_competing_forks():
    f = Funk()
    f.txn_prepare(ROOT_XID, _xid(1))
    f.txn_prepare(_xid(1), _xid(2))
    f.txn_prepare(_xid(1), _xid(3))  # competing sibling
    f.txn_prepare(ROOT_XID, _xid(4))  # competing top-level fork
    f.rec_write(_xid(2), b"k", b"winner")
    f.rec_write(_xid(3), b"k", b"loser")
    f.rec_write(_xid(4), b"k", b"loser2")
    assert f.txn_publish(_xid(2)) == 2  # publishes x1 then x2
    assert f.rec_read(ROOT_XID, b"k") == b"winner"
    assert f.txns == {}  # all competing forks cancelled


def test_publish_reparents_survivors():
    f = Funk()
    f.txn_prepare(ROOT_XID, _xid(1))
    f.rec_write(_xid(1), b"k", b"v")
    f.txn_prepare(_xid(1), _xid(2))
    f.txn_publish(_xid(1))
    assert _xid(2) in f.txns
    assert f.txns[_xid(2)].parent == ROOT_XID
    assert f.rec_read(_xid(2), b"k") == b"v"


def test_frozen_rule():
    f = Funk()
    f.txn_prepare(ROOT_XID, _xid(1))
    with pytest.raises(AssertionError):
        f.rec_write(ROOT_XID, b"k", b"v")  # root frozen while fork open
    f.txn_prepare(_xid(1), _xid(2))
    with pytest.raises(AssertionError):
        f.rec_write(_xid(1), b"k", b"v")  # parent frozen
    f.rec_write(_xid(2), b"k", b"v")  # frontier ok


def test_cancel_subtree():
    f = Funk()
    f.txn_prepare(ROOT_XID, _xid(1))
    f.txn_prepare(_xid(1), _xid(2))
    f.txn_prepare(_xid(2), _xid(3))
    assert f.txn_cancel(_xid(2)) == 2
    assert _xid(1) in f.txns and _xid(2) not in f.txns and _xid(3) not in f.txns


def test_batch_read_matrix():
    f = Funk()
    f.rec_write(ROOT_XID, b"a", b"xx")
    f.rec_write(ROOT_XID, b"b", b"yyyy")
    rows, lens, found = f.rec_read_batch(ROOT_XID, [b"a", b"missing", b"b"], 8)
    assert found.tolist() == [True, False, True]
    assert lens.tolist() == [2, 0, 4]
    assert bytes(rows[0, :2]) == b"xx"
    assert (rows[1] == 0).all()
    assert bytes(rows[2, :4]) == b"yyyy"


def test_checkpoint_restore(tmp_path):
    f = Funk()
    f.rec_write(ROOT_XID, b"k1", b"v1")
    f.rec_write(ROOT_XID, b"k2", b"v2" * 100)
    path = str(tmp_path / "funk.ckpt")
    f.checkpoint(path)
    g = Funk.restore(path)
    assert g.root == f.root
    with pytest.raises(AssertionError):
        bad = str(tmp_path / "bad.ckpt")
        open(bad, "wb").write(b"garbage!")
        Funk.restore(bad)


def test_rec_write_many_batch_semantics():
    """Batch write-back (the bank table's commit path): one frozen
    check, None removes, every touched key drops out of lam_cache."""
    f = Funk()
    f.rec_write(ROOT_XID, b"a", b"old")
    f.rec_write(ROOT_XID, b"gone", b"x")
    f.lam_cache[b"a"] = 123
    f.lam_cache[b"gone"] = 7
    f.rec_write_many(ROOT_XID, [(b"a", b"new"), (b"b", b"v"), (b"gone", None)])
    assert f.root[b"a"] == b"new" and f.root[b"b"] == b"v"
    assert b"gone" not in f.root
    assert b"a" not in f.lam_cache and b"gone" not in f.lam_cache
    # txn writes shadow (None is the tombstone) and respect frozen
    f.txn_prepare(ROOT_XID, b"\x01" * 32)
    f.rec_write_many(b"\x01" * 32, [(b"a", None), (b"c", b"cc")])
    assert f.rec_read(b"\x01" * 32, b"a") is None
    assert f.rec_read(b"\x01" * 32, b"c") == b"cc"
    assert f.root[b"a"] == b"new"
    with pytest.raises(AssertionError):
        f.rec_write_many(ROOT_XID, [(b"z", b"1")])  # root frozen now
