"""PoH chain ops vs a hashlib oracle."""

import hashlib

import jax
import numpy as np

from firedancer_tpu.ops import poh
import pytest

pytestmark = pytest.mark.slow


def _append_ref(state: bytes, n: int) -> bytes:
    for _ in range(n):
        state = hashlib.sha256(state).digest()
    return state


def _mixin_ref(state: bytes, mix: bytes) -> bytes:
    return hashlib.sha256(state + mix).digest()


def test_append_n():
    rng = np.random.default_rng(0)
    state = rng.integers(0, 256, size=(1, 32), dtype=np.uint8)
    out = np.asarray(jax.jit(lambda s: poh.append_n(s, 17))(state))
    assert out[0].tobytes() == _append_ref(state[0].tobytes(), 17)


def test_mixin():
    rng = np.random.default_rng(1)
    state = rng.integers(0, 256, size=(4, 32), dtype=np.uint8)
    mix = rng.integers(0, 256, size=(4, 32), dtype=np.uint8)
    out = np.asarray(poh.mixin(state, mix))
    for i in range(4):
        assert out[i].tobytes() == _mixin_ref(
            state[i].tobytes(), mix[i].tobytes()
        )


def test_verify_entries():
    rng = np.random.default_rng(2)
    b = 16
    starts = rng.integers(0, 256, size=(b, 32), dtype=np.uint8)
    hashcnts = rng.integers(1, 12, size=b).astype(np.int32)
    mixins = rng.integers(0, 256, size=(b, 32), dtype=np.uint8)
    has_mixin = rng.integers(0, 2, size=b).astype(bool)
    out = np.asarray(
        poh.verify_entries(starts, hashcnts, mixins, has_mixin, 12)
    )
    for i in range(b):
        st = _append_ref(
            starts[i].tobytes(),
            int(hashcnts[i]) - (1 if has_mixin[i] else 0),
        )
        if has_mixin[i]:
            st = _mixin_ref(st, mixins[i].tobytes())
        assert out[i].tobytes() == st, f"lane {i}"
